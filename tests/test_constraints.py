"""Monotone / interaction constraints, bynode sampling, path smoothing, extra_trees.

Mirrors the reference's tests/python_package_test/test_engine.py monotone- and
interaction-constraint tests (is_increasing/is_non_monotone checks;
src/treelearner/monotone_constraints.hpp basic method)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _monotone_data(n=2000, seed=3):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 3)
    y = (5 * x[:, 0] + np.sin(10 * np.pi * x[:, 0])
         - 5 * x[:, 1] - np.cos(10 * np.pi * x[:, 1])
         + rs.rand(n) + 10 * x[:, 2])
    return x, y


def _is_increasing(bst, feat, n=200):
    xs = np.linspace(0.01, 0.99, n)
    X = np.full((n, 3), 0.5)
    X[:, feat] = xs
    p = bst.predict(X)
    return np.all(np.diff(p) >= -1e-9)


def _is_decreasing(bst, feat, n=200):
    xs = np.linspace(0.01, 0.99, n)
    X = np.full((n, 3), 0.5)
    X[:, feat] = xs
    p = bst.predict(X)
    return np.all(np.diff(p) <= 1e-9)


def test_monotone_constraints_basic():
    X, y = _monotone_data()
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "monotone_constraints": [1, -1, 0], "min_data_in_leaf": 5}
    bst = lgb.train(params, ds, num_boost_round=30)
    assert _is_increasing(bst, 0)
    assert _is_decreasing(bst, 1)
    # feature 2 is unconstrained and drives y: model must still fit reasonably
    pred = bst.predict(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.8


def test_monotone_unconstrained_violates():
    # sanity: without constraints the wiggly components break monotonicity
    X, y = _monotone_data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    ds, num_boost_round=30)
    assert not (_is_increasing(bst, 0) and _is_decreasing(bst, 1))


def test_monotone_penalty_and_methods():
    X, y = _monotone_data()
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "monotone_constraints": [1, -1, 0], "monotone_penalty": 2.0,
              "min_data_in_leaf": 5}
    bst = lgb.train(params, ds, num_boost_round=20)
    assert _is_increasing(bst, 0)
    assert _is_decreasing(bst, 1)


def test_interaction_constraints():
    rs = np.random.RandomState(5)
    n, f = 3000, 6
    X = rs.rand(n, f)
    y = X[:, 0] * X[:, 1] + X[:, 2] + 0.1 * rs.randn(n)
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "interaction_constraints": [[0, 1], [2, 3, 4, 5]],
              "min_data_in_leaf": 5}
    bst = lgb.train(params, ds, num_boost_round=20)
    # every tree's feature set must lie inside one constraint group
    dump = bst.dump_model()
    groups = [{0, 1}, {2, 3, 4, 5}]

    def path_feats(node, path, out):
        if "split_feature" in node:
            p2 = path | {node["split_feature"]}
            path_feats(node["left_child"], p2, out)
            path_feats(node["right_child"], p2, out)
        else:
            if path:
                out.append(path)

    for tinfo in dump["tree_info"]:
        paths = []
        path_feats(tinfo["tree_structure"], set(), paths)
        for p in paths:
            assert any(p <= g for g in groups), f"path {p} violates constraints"


def test_feature_fraction_bynode():
    rs = np.random.RandomState(6)
    X = rs.rand(1500, 10)
    y = X @ rs.rand(10) + 0.05 * rs.randn(1500)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "feature_fraction_bynode": 0.5, "verbosity": -1},
                    ds, num_boost_round=10)
    pred = bst.predict(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.8


def test_path_smooth_and_extra_trees():
    rs = np.random.RandomState(7)
    X = rs.rand(1500, 5)
    y = X @ rs.rand(5) + 0.05 * rs.randn(1500)
    ds = lgb.Dataset(X, label=y)
    b1 = lgb.train({"objective": "regression", "num_leaves": 15,
                    "path_smooth": 10.0, "verbosity": -1}, ds,
                   num_boost_round=10)
    b2 = lgb.train({"objective": "regression", "num_leaves": 15,
                    "extra_trees": True, "verbosity": -1}, ds,
                   num_boost_round=10)
    for b in (b1, b2):
        assert np.corrcoef(b.predict(X), y)[0, 1] > 0.7


def test_unimplemented_params_raise():
    X = np.random.rand(100, 3)
    y = np.random.rand(100)
    # invalid enums, wrong-sized penalty vectors and missing forced-splits
    # files must fail loudly
    for bad in ({"cegb_penalty_feature_lazy": [1.0]},          # wrong length
                {"hist_precision": "quad"},
                {"forcedsplits_filename": "/nonexistent/f.json"}):
        ds = lgb.Dataset(X, label=y)
        params = {"objective": "regression", "verbosity": -1, **bad}
        with pytest.raises(lgb.LightGBMError):
            lgb.train(params, ds, num_boost_round=2)


def _monotone_fit(method, seed=5, n=2500):
    rs = np.random.RandomState(seed)
    X = rs.rand(n, 4)
    y = (2.0 * X[:, 0] + np.sin(6 * X[:, 1]) - 1.2 * X[:, 2]
         + 0.15 * rs.randn(n))
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "monotone_constraints": [1, 0, -1, 0],
                     "monotone_constraints_method": method},
                    ds, num_boost_round=25)
    return bst, X, y


def _check_monotone(bst, n_probe=200, seed=0):
    """Sweep each constrained feature over its range with all other features
    fixed; predictions must be monotone in the required direction."""
    rs = np.random.RandomState(seed)
    base = rs.rand(n_probe, 4)
    grid = np.linspace(0.01, 0.99, 25)
    for feat, direction in ((0, 1), (2, -1)):
        preds = []
        for g in grid:
            Xp = base.copy()
            Xp[:, feat] = g
            preds.append(bst.predict(Xp))
        P = np.stack(preds)                     # (grid, probe)
        diffs = np.diff(P, axis=0) * direction
        assert np.all(diffs >= -1e-10), (feat, direction, diffs.min())


@pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
def test_monotone_methods_enforce_monotonicity(method):
    """Both constraint methods must produce truly monotone models
    (reference: monotone_constraints.hpp Basic/IntermediateLeafConstraints)."""
    bst, X, y = _monotone_fit(method)
    _check_monotone(bst)


@pytest.mark.slow
def test_intermediate_fits_at_least_as_well_as_basic():
    """The intermediate method's refreshed bounds are less conservative than
    basic's frozen midpoints, so its fit should not be worse (reference:
    monotone_constraints.hpp motivation)."""
    b_basic, X, y = _monotone_fit("basic")
    b_inter, _, _ = _monotone_fit("intermediate")
    mse_basic = float(np.mean((b_basic.predict(X) - y) ** 2))
    mse_inter = float(np.mean((b_inter.predict(X) - y) ** 2))
    assert mse_inter <= mse_basic * 1.02, (mse_inter, mse_basic)
