"""Continued training (init_model) + periodic snapshots.

Reference: src/boosting/boosting.cpp:42-90 (model continuation),
src/boosting/gbdt.cpp:259-263 (snapshot_freq), engine.py init_model."""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow  # heavy multi-model tier (PERF.md test tiers)


def _data(n=1500, seed=4):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 8)
    y = X @ rs.rand(8) + 0.1 * rs.randn(n)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5, "learning_rate": 0.1}


def test_continued_training_matches_straight_run(tmp_path):
    X, y = _data()
    bst20 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=20)

    bst10 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "m10.txt")
    bst10.save_model(path)

    # continue from file
    bst_cont = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10,
                         init_model=path)
    assert bst_cont.num_trees() == 20
    p20 = bst20.predict(X)
    pc = bst_cont.predict(X)
    # growth is deterministic given the same scores; thresholds requantize
    # through the text model round-trip, so allow tiny drift
    np.testing.assert_allclose(pc, p20, rtol=1e-4, atol=1e-4)

    # continue from an in-memory Booster too
    bst_cont2 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10,
                          init_model=bst10)
    np.testing.assert_allclose(bst_cont2.predict(X), p20, rtol=1e-4, atol=1e-4)
    # the caller's booster must be untouched by continuation
    np.testing.assert_allclose(bst10.predict(X),
                               lgb.Booster(model_file=path).predict(X),
                               rtol=1e-9)


def test_continued_training_with_valid_sets():
    X, y = _data()
    Xv, yv = _data(400, seed=9)
    bst10 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    ev = {}
    train_ds = lgb.Dataset(X, label=y)
    bst = lgb.train(PARAMS, train_ds, num_boost_round=5,
                    valid_sets=[lgb.Dataset(Xv, label=yv, reference=train_ds)],
                    valid_names=["v"], init_model=bst10,
                    callbacks=[lgb.record_evaluation(ev)])
    assert bst.num_trees() == 15
    assert len(ev["v"]["l2"]) == 5
    # valid metric must reflect the loaded trees (far better than from-scratch)
    first_l2 = ev["v"]["l2"][0]
    base_l2 = float(np.mean((yv - np.mean(y)) ** 2))
    assert first_l2 < base_l2 * 0.8


def test_num_leaves_budget_guard(tmp_path):
    X, y = _data()
    big = lgb.train({**PARAMS, "num_leaves": 31},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    path = str(tmp_path / "big.txt")
    big.save_model(path)
    with pytest.raises(lgb.LightGBMError):
        lgb.train({**PARAMS, "num_leaves": 8}, lgb.Dataset(X, label=y),
                  num_boost_round=2, init_model=path)


def test_snapshot_freq(tmp_path):
    X, y = _data()
    out = str(tmp_path / "model.txt")
    lgb.train({**PARAMS, "snapshot_freq": 3, "output_model": out},
              lgb.Dataset(X, label=y), num_boost_round=7)
    snaps = sorted(os.listdir(tmp_path))
    assert f"{os.path.basename(out)}.snapshot_iter_3" in snaps
    assert f"{os.path.basename(out)}.snapshot_iter_6" in snaps
    loaded = lgb.Booster(model_file=out + ".snapshot_iter_6")
    assert loaded.num_trees() == 6
