"""Compiled-program cost observability (docs/OBSERVABILITY.md "Cost model
& profiling"): XLA flops/HBM capture per watched_jit entry, roofline
verdicts, the AOT compile/execute accounting fix, counter resets, the
host+device profile session, and the perf-regression sentinel."""
import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.telemetry as tel
from lightgbm_tpu.telemetry import costmodel

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def telemetry_cost():
    tel.reset()
    tel.reset_watchdog()
    tel.reset_counters()
    tel.configure(enabled=True, cost_capture="full")
    yield tel
    tel.configure(enabled=False, metrics_out="", trace_out="",
                  cost_capture="auto")
    tel.reset()
    tel.reset_watchdog()
    tel.reset_counters()


def _sentinel():
    spec = importlib.util.spec_from_file_location(
        "perf_sentinel", REPO / "scripts" / "perf_sentinel.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _train(params_extra=None, rows=1500, iters=3):
    rs = np.random.RandomState(3)
    X = rs.randn(rows, 8).astype(np.float32)
    y = (X[:, 0] + 0.3 * rs.randn(rows) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "telemetry": True, **(params_extra or {})}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=iters), X


# ---------------------------------------------------------------------------
# capture: training entries, summary/metrics/JSONL export
# ---------------------------------------------------------------------------

def test_training_entries_have_full_cost_records(telemetry_cost):
    bst, _ = _train({"telemetry_cost": "full"})
    cost = bst.telemetry_summary()["cost"]
    assert cost["enabled"] and cost["mode"] == "full"
    for name in ("grow_tree", "gradients"):
        rec = cost["entries"][name]
        assert rec["available"]
        assert rec["flops"] > 0
        assert rec["bytes_accessed"] > 0
        assert rec["peak_hbm_bytes"] > 0
        assert rec["verdict"] in ("compute-bound", "hbm-bound")
        assert rec["intensity"] == pytest.approx(
            rec["flops"] / rec["bytes_accessed"], rel=1e-3)
    # the roofline the verdicts were judged against rides along
    assert cost["roofline"]["ridge_intensity"] > 0
    # dispatch-weighted totals accumulated across the run
    assert cost["totals"]["flops"] > 0
    assert cost["totals"]["hbm_bytes"] > 0


def test_per_iteration_records_carry_flops_and_bytes(telemetry_cost):
    _train({"telemetry_cost": "full"}, iters=4)
    recs = [r for r in tel.global_registry.records
            if r.get("event") == "iteration"]
    assert len(recs) == 4
    # steady-state iterations execute the captured programs, so the
    # per-iteration flops/hbm_bytes fields are positive
    assert all(r["flops"] > 0 for r in recs[1:])
    assert all(r["hbm_bytes"] > 0 for r in recs[1:])
    snap = tel.global_registry.snapshot()
    assert snap["counters"]["cost/flops"] > 0
    assert snap["counters"]["cost/hbm_bytes"] > 0


def test_cost_gauges_reach_prometheus_exposition(telemetry_cost):
    _train({"telemetry_cost": "full"})
    text = tel.registry_text()
    assert "# TYPE lgbtpu_cost_grow_tree_flops gauge" in text
    assert "lgbtpu_cost_grow_tree_peak_hbm_bytes" in text
    assert "lgbtpu_cost_gradients_flops" in text


def test_lowered_mode_skips_the_second_compile(telemetry_cost):
    tel.configure(enabled=True, cost_capture="lowered")
    _train()   # params telemetry only; configured mode stays "lowered"
    recs = costmodel.cost_records()
    rec = recs["grow_tree"]
    assert rec["available"] and rec["source"] == "lowered"
    assert rec["flops"] > 0
    # memory analysis needs the compiled executable — absent by design
    assert "peak_hbm_bytes" not in rec


@pytest.mark.skipif(len(jax.devices()) < 4,
                    reason="needs a >=4-device mesh")
def test_fused_iter_has_a_cost_record(telemetry_cost):
    """The one-launch-per-iteration mesh program is the most expensive
    entry in the system — its cost record is the headline attribution."""
    rs = np.random.RandomState(5)
    X = rs.randn(4096, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "telemetry": True,
                     "telemetry_cost": "full", "tree_learner": "data",
                     "hist_backend": "stream", "mesh_shape": "data:4"},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst.engine._fused_last, "fused path did not engage"
    rec = bst.telemetry_summary()["cost"]["entries"]["fused_iter"]
    assert rec["available"]
    assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
    assert rec["peak_hbm_bytes"] > 0
    assert rec["verdict"] in ("compute-bound", "hbm-bound")


def test_serve_predict_has_a_cost_record(telemetry_cost, tmp_path):
    bst, X = _train()
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    from lightgbm_tpu.serving.registry import ModelRegistry
    reg = ModelRegistry(path, max_batch=16)
    reg.current().predict(X[:4], raw_score=True)
    rec = costmodel.cost_records()["serve_predict"]
    assert rec["available"] and rec["flops"] >= 0
    assert rec["verdict"] in ("compute-bound", "hbm-bound")


# ---------------------------------------------------------------------------
# roofline classification
# ---------------------------------------------------------------------------

def test_roofline_verdict_splits_on_the_ridge(monkeypatch):
    monkeypatch.setattr(costmodel, "_balance", None)
    monkeypatch.setenv("LGBTPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("LGBTPU_PEAK_BW", "1e10")   # ridge = 100 flops/byte
    try:
        assert costmodel.machine_balance()["ridge_intensity"] == 100.0
        assert costmodel.roofline_verdict(1e9, 1e6)["verdict"] == \
            "compute-bound"    # intensity 1000
        assert costmodel.roofline_verdict(1e6, 1e6)["verdict"] == \
            "hbm-bound"        # intensity 1
        assert costmodel.roofline_verdict(1.0, 0.0)["verdict"] == \
            "unavailable"
    finally:
        costmodel._balance = None   # drop the env-poisoned cache


# ---------------------------------------------------------------------------
# AOT surface + counter resets (watchdog satellites)
# ---------------------------------------------------------------------------

def test_aot_lower_compile_counts_and_captures(telemetry_cost):
    f = tel.watched_jit(lambda x: x * 2.0 + 1.0, name="aot_entry",
                        warn_after=0)
    x = jnp.ones((32,), jnp.float32)
    compiled = f.lower(x).compile()
    # the AOT compile is on the books: one trace for the entry
    assert tel.recompile_counts()["aot_entry"] == 1
    assert tel.global_registry.snapshot()["counters"][
        "recompile/aot_entry"] == 1
    # ... and the compiled executable was captured for free
    rec = costmodel.cost_records()["aot_entry"]
    assert rec["available"] and rec["source"] == "aot"
    assert rec["peak_hbm_bytes"] > 0
    # executions through the AOT object count as launches
    l0 = tel.launch_count()
    f0, _ = costmodel.dispatch_totals()
    compiled(x)
    assert tel.launch_count() == l0 + 1
    assert costmodel.dispatch_totals()[0] > f0


def test_aot_compile_of_warm_signature_still_counts(telemetry_cost):
    f = tel.watched_jit(lambda x: x + 1.0, name="aot_warm", warn_after=0)
    x = jnp.ones((8,), jnp.float32)
    f(x)    # normal dispatch traces + compiles
    assert tel.recompile_counts()["aot_warm"] == 1
    # lower() now hits the jaxpr cache, but .compile() is a REAL second
    # XLA compile of the entry — it must not vanish from the counters
    f.lower(x).compile()
    assert tel.recompile_counts()["aot_warm"] == 2


def test_reset_counters_zeroes_the_globals(telemetry_cost):
    f = tel.watched_jit(lambda x: x - 1.0, name="reset_probe",
                        warn_after=0)
    f(jnp.ones((4,), jnp.float32))
    tel.note_host_sync()
    assert tel.launch_count() > 0 and tel.host_sync_count() > 0
    tel.reset_counters()
    assert tel.launch_count() == 0 and tel.host_sync_count() == 0


# ---------------------------------------------------------------------------
# graceful degradation: unavailable is never zero
# ---------------------------------------------------------------------------

class _RaisingJit:
    def lower(self, *a, **k):
        raise RuntimeError("backend refuses AOT lowering")


class _EmptyCostLowered:
    def cost_analysis(self):
        return {}

    def compile(self):
        raise RuntimeError("no compile either")


class _EmptyCostJit:
    def lower(self, *a, **k):
        return _EmptyCostLowered()


def _fresh_entry(name):
    e = tel.WatchEntry(name, 0)
    e.count = 1   # one trace happened, nothing captured yet
    return e


def test_capture_failure_yields_unavailable_not_zero(telemetry_cost):
    t0 = costmodel.dispatch_totals()
    entry = _fresh_entry("degraded_raise")
    costmodel.after_dispatch(entry, _RaisingJit(), (), {})
    rec = costmodel.cost_records()["degraded_raise"]
    assert rec["available"] is False
    assert rec["verdict"] == "unavailable"
    assert "flops" not in rec     # no fabricated zero
    # unavailable entries contribute nothing to the totals
    assert costmodel.dispatch_totals() == t0
    # and the capture is not retried every dispatch
    assert entry.cost_seen == entry.count


def test_empty_cost_analysis_is_unavailable(telemetry_cost):
    entry = _fresh_entry("degraded_empty")
    costmodel.after_dispatch(entry, _EmptyCostJit(), (), {})
    rec = costmodel.cost_records()["degraded_empty"]
    assert rec["available"] is False and rec["verdict"] == "unavailable"


def test_sentinel_skips_unavailable_entries():
    sentinel = _sentinel()
    measured = {"entries": {"grow_tree": {"available": False,
                                          "error": "no cost analysis"}},
                "launches_per_iter": 1.0}
    budgets = {"tolerance": 0.1,
               "entries": {"grow_tree": {"flops": 1.0}}}   # absurdly low
    violations, skipped, checks = sentinel.compare_budgets(measured,
                                                           budgets)
    # an unavailable measurement must SKIP (with a notice), never pass as
    # a 0-flops "100% improvement" nor fail the absurd budget
    assert violations == [] and checks == 0
    assert any("unavailable" in s for s in skipped)


# ---------------------------------------------------------------------------
# perf sentinel: budgets + history
# ---------------------------------------------------------------------------

def test_sentinel_budget_compare_pass_and_fail():
    sentinel = _sentinel()
    measured = {"entries": {"grow_tree": {"flops": 100.0,
                                          "peak_hbm_bytes": 1000.0}},
                "launches_per_iter": 3.0}
    budgets = {"tolerance": 0.1, "launches_per_iter_max": 5,
               "entries": {"grow_tree": {"flops": 120,
                                         "peak_hbm_bytes": 1100}}}
    violations, _, checks = sentinel.compare_budgets(measured, budgets)
    assert violations == [] and checks == 3
    bad = {"tolerance": 0.1, "launches_per_iter_max": 2,
           "entries": {"grow_tree": {"flops": 80}}}
    violations, _, _ = sentinel.compare_budgets(measured, bad)
    assert len(violations) == 2
    assert any("grow_tree.flops" in v for v in violations)
    assert any("launches_per_iter" in v for v in violations)


def test_sentinel_cli_exit_codes(tmp_path):
    measured = {"entries": {"grow_tree": {"flops": 100.0}},
                "launches_per_iter": 1.0}
    cur = tmp_path / "current.json"
    cur.write_text(json.dumps(measured))
    ok_budget = tmp_path / "ok.json"
    ok_budget.write_text(json.dumps(
        {"entries": {"grow_tree": {"flops": 200}}}))
    bad_budget = tmp_path / "bad.json"
    bad_budget.write_text(json.dumps(
        {"entries": {"grow_tree": {"flops": 10}}}))
    script = str(REPO / "scripts" / "perf_sentinel.py")

    def run(budget):
        return subprocess.run(
            [sys.executable, script, "--budgets", str(budget),
             "--current", str(cur)],
            capture_output=True, text=True, timeout=60)

    assert run(ok_budget).returncode == 0
    r = run(bad_budget)
    assert r.returncode == 1
    assert "REGRESSION" in r.stderr


def test_repo_budgets_manifest_is_well_formed():
    """PERF_BUDGETS.json stays loadable and covers the tier-1 training
    entries (the full measured gate runs in run_all_tests.sh)."""
    budgets = json.loads((REPO / "PERF_BUDGETS.json").read_text())
    assert 0 < budgets["tolerance"] < 1
    for name in ("grow_tree", "gradients", "serve_predict"):
        entry = budgets["entries"][name]
        assert entry["flops"] > 0 and entry["peak_hbm_bytes"] > 0


def _hist_line(metric, value, date, host="box"):
    return json.dumps({"metric": metric, "value": value, "date": date,
                       "host": host}) + "\n"


def test_sentinel_history_regression_and_direction(tmp_path):
    sentinel = _sentinel()
    hist = tmp_path / "hist.jsonl"
    hist.write_text(
        _hist_line("x_s_per_tree", 1.00, "2026-01-01") +
        _hist_line("x_s_per_tree", 1.02, "2026-01-02") +
        _hist_line("x_s_per_tree", 2.50, "2026-01-03") +     # slower: bad
        _hist_line("serve_qps", 100.0, "2026-01-01") +
        _hist_line("serve_qps", 102.0, "2026-01-02") +
        _hist_line("serve_qps", 40.0, "2026-01-03") +        # slower: bad
        _hist_line("young_metric", 5.0, "2026-01-03"))       # < min_runs
    violations, notices, checks = sentinel.check_history(
        str(hist), tolerance=0.25, min_runs=3)
    assert checks == 2 and len(violations) == 2
    assert any("x_s_per_tree" in v for v in violations)
    assert any("serve_qps" in v for v in violations)
    assert any("young_metric" in n for n in notices)
    # same data, healthy latest values -> clean
    hist.write_text(
        _hist_line("x_s_per_tree", 1.00, "2026-01-01") +
        _hist_line("x_s_per_tree", 1.02, "2026-01-02") +
        _hist_line("x_s_per_tree", 0.97, "2026-01-03") +
        _hist_line("serve_qps", 100.0, "2026-01-01") +
        _hist_line("serve_qps", 102.0, "2026-01-02") +
        _hist_line("serve_qps", 108.0, "2026-01-03"))
    violations, _, checks = sentinel.check_history(str(hist))
    assert violations == [] and checks == 2


def test_repo_history_file_is_well_formed():
    """The committed BENCH_HISTORY.jsonl (seeded from the BENCH_r0*
    archives) parses as one record per line with the fields the
    sentinel keys on.  The live regression gate over this file runs in
    run_all_tests.sh — re-running it here would couple the unit suite
    to mutable bench data."""
    lines = (REPO / "BENCH_HISTORY.jsonl").read_text().splitlines()
    assert lines
    for line in lines:
        row = json.loads(line)
        assert isinstance(row["metric"], str)
        assert isinstance(row["value"], (int, float))
        assert row["date"]


# ---------------------------------------------------------------------------
# profile session: one merged host+device Perfetto timeline
# ---------------------------------------------------------------------------

def test_profile_session_merges_host_and_device_trace(telemetry_cost,
                                                      tmp_path):
    from lightgbm_tpu.telemetry.profile import ProfileSession
    out = tmp_path / "prof"
    session = ProfileSession(str(out)).start()
    try:
        with tel.span("ProfiledRegion"):
            f = tel.watched_jit(lambda x: (x @ x).sum(),
                                name="profiled_mm", warn_after=0)
            f(jnp.ones((64, 64), jnp.float32)).block_until_ready()
    finally:
        info = session.stop()
    assert info.get("device_trace_error") is None, info
    assert info["shards"] == 2
    blob = json.loads(Path(info["merged_trace"]).read_text())
    names = {e.get("name") for e in blob["traceEvents"]}
    # host span and device-side events share one timeline
    assert "ProfiledRegion" in names
    shard_info = blob["otherData"]["shards"]
    assert len(shard_info) == 2 and all(s["aligned"] for s in shard_info)
    device_events = [s["events"] for s in shard_info
                     if "device" in s["path"]][0]
    assert device_events > 0
