"""Distributed data ingestion (reference: DatasetLoader::LoadFromFile rank
sharding + bin-mapper sync, dataset_loader.cpp:211,733-741; test model:
tests/distributed/_test_distributed.py — localhost multi-process).

The 2-process test launches real `jax.distributed` processes on localhost;
each parses a DISJOINT shard of the csv, mappers sync via allgather, the
binned shards assemble into one global row-sharded array, and the trained
model must match single-process training on the full file.
"""
import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset_io import load_data_file

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write_csv(path, n=4000, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + np.sin(X[:, 1]) + 0.1 * rng.randn(n) > 0).astype(float)
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.10g")
    return X, y


def test_shard_loading_concat_equals_full(tmp_path):
    p = str(tmp_path / "d.csv")
    X, y = _write_csv(p)
    w = np.random.RandomState(1).rand(len(X))
    np.savetxt(p + ".weight", w, fmt="%.8f")
    full_X, full_y, full_ex = load_data_file(p, {})
    parts = [load_data_file(p, {}, rank=r, num_machines=3) for r in range(3)]
    np.testing.assert_allclose(np.vstack([q[0] for q in parts]), full_X)
    np.testing.assert_allclose(np.concatenate([q[1] for q in parts]), full_y)
    np.testing.assert_allclose(
        np.concatenate([q[2]["weight"] for q in parts]), full_ex["weight"])
    starts = [q[2]["start_row"] for q in parts]
    assert starts == [0, len(parts[0][0]), len(parts[0][0]) + len(parts[1][0])]


_CHILD = r"""
import os, sys
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
try:  # cross-process CPU collectives (older jax: option absent)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
port, rank, data, out = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=rank)
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import lightgbm_tpu as lgb
ds = lgb.Dataset(data)
bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                 "min_data_in_leaf": 5, "tree_learner": "data",
                 "hist_backend": "stream"},
                ds, num_boost_round=5)
assert ds._dist is not None and ds._dist["nproc"] == 2
if rank == 0:
    open(out, "w").write(bst.model_to_string())
"""


def _models_structurally_equal(a: str, b: str):
    a = a.split("\nparameters:")[0]
    b = b.split("\nparameters:")[0]
    la, lb = a.splitlines(), b.splitlines()
    assert len(la) == len(lb)
    for xa, xb in zip(la, lb):
        if xa == xb:
            continue
        ka, _, va = xa.partition("=")
        kb, _, vb = xb.partition("=")
        assert ka == kb
        if ka == "tree_sizes":
            continue
        fa = np.array([float(t) for t in va.split()])
        fb = np.array([float(t) for t in vb.split()])
        np.testing.assert_allclose(fa, fb, rtol=3e-4, atol=3e-4, err_msg=ka)


@pytest.mark.slow
def test_two_process_distributed_training(tmp_path, require_two_process_collectives):
    data = str(tmp_path / "train.csv")
    _write_csv(data)
    out = str(tmp_path / "dist_model.txt")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(port), str(r), data, out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-4000:]}"

    # single-process reference on the full file
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "hist_backend": "stream"},
                    lgb.Dataset(data), num_boost_round=5)
    dist_model = open(out).read()
    _models_structurally_equal(bst.model_to_string(), dist_model)


_CHILD_VALID = r"""
import os, sys, json
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
try:  # cross-process CPU collectives (older jax: option absent)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
port, rank, data, vdata, out = (sys.argv[1], int(sys.argv[2]), sys.argv[3],
                                sys.argv[4], sys.argv[5])
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=rank)
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
import lightgbm_tpu as lgb
ds = lgb.Dataset(data)
vs = lgb.Dataset(vdata, reference=ds)
evals = {}
bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                 "min_data_in_leaf": 5, "tree_learner": "data",
                 "metric": "binary_logloss"},
                ds, num_boost_round=30, valid_sets=[vs],
                valid_names=["valid"],
                callbacks=[lgb.early_stopping(3, verbose=False),
                           lgb.record_evaluation(evals)])
if rank == 0:
    json.dump({"best_iteration": bst.best_iteration,
               "logloss": evals["valid"]["binary_logloss"]}, open(out, "w"))
"""


@pytest.mark.slow
def test_two_process_valid_early_stopping_matches_single(
        tmp_path, require_two_process_collectives):
    """Rank-aligned validation under distributed loading (reference:
    LoadFromFileAlignWithOtherDataset): early stopping must pick the same
    best_iteration as single-process training on the full files."""
    data = str(tmp_path / "train.csv")
    vdata = str(tmp_path / "valid.csv")
    _write_csv(data)
    _write_csv(vdata, n=1200, seed=9)
    out = str(tmp_path / "dist_es.json")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD_VALID, str(port), str(r), data, vdata,
         out], env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-4000:]}"
    import json
    got = json.load(open(out))

    evals = {}
    ds = lgb.Dataset(data)
    vs = lgb.Dataset(vdata, reference=ds)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "metric": "binary_logloss"},
                    ds, num_boost_round=30, valid_sets=[vs],
                    valid_names=["valid"],
                    callbacks=[lgb.early_stopping(3, verbose=False),
                               lgb.record_evaluation(evals)])
    assert got["best_iteration"] == bst.best_iteration
    np.testing.assert_allclose(got["logloss"],
                               evals["valid"]["binary_logloss"],
                               rtol=2e-3, atol=2e-3)


_CHILD_RANK = r"""
import os, sys
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
try:  # cross-process CPU collectives (older jax: option absent)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
port, rank, data, out = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=rank)
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
import lightgbm_tpu as lgb
ds = lgb.Dataset(data)
bst = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                 "verbosity": -1, "min_data_in_leaf": 5,
                 "tree_learner": "data"},
                ds, num_boost_round=5)
assert ds.get_group() is not None
if rank == 0:
    open(out, "w").write(bst.model_to_string())
"""


def _write_ranking_csv(path, nq=120, seed=3):
    rng = np.random.RandomState(seed)
    sizes = rng.randint(5, 30, size=nq)
    n = int(sizes.sum())
    X = rng.randn(n, 5)
    rel = X[:, 0] * 2 + X[:, 1] + 0.3 * rng.randn(n)
    y = np.zeros(n)
    start = 0
    for s in sizes:
        seg = rel[start:start + s]
        ranks = np.argsort(np.argsort(seg))
        y[start:start + s] = np.minimum(4, (ranks * 5) // max(s, 1))
        start += s
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.10g")
    np.savetxt(path + ".query", sizes, fmt="%d")
    return sizes


@pytest.mark.slow
def test_two_process_lambdarank_matches_single(
        tmp_path, require_two_process_collectives):
    """Query-boundary-respecting sharding: lambdarank under multi-process
    tree_learner=data must reproduce single-process training."""
    data = str(tmp_path / "rank.csv")
    _write_ranking_csv(data)
    out = str(tmp_path / "dist_rank_model.txt")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD_RANK, str(port), str(r), data, out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-4000:]}"

    bst = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(data), num_boost_round=5)
    _models_structurally_equal(bst.model_to_string(), open(out).read())


def test_query_aligned_sharding_keeps_queries_whole(tmp_path):
    p = str(tmp_path / "r.csv")
    sizes = _write_ranking_csv(p, nq=37, seed=5)
    parts = [load_data_file(p, {}, rank=r, num_machines=3) for r in range(3)]
    gs = [q[2]["group"] for q in parts]
    np.testing.assert_array_equal(np.concatenate(gs), sizes)
    assert sum(len(q[0]) for q in parts) == int(sizes.sum())
    for q in parts:
        assert int(q[2]["group"].sum()) == len(q[0])


def test_streamed_query_aligned_shards(tmp_path):
    """Streaming ingest shards ranking files on QUERY boundaries: every
    rank's chunk stream reproduces exactly a whole-query row slice (no
    query straddles a shard) and the group slices concatenate back to the
    full .query sidecar."""
    from lightgbm_tpu.ingest import _FileSource

    p = str(tmp_path / "r.csv")
    sizes = _write_ranking_csv(p, nq=37, seed=5)
    full = np.loadtxt(p, delimiter=",")
    bounds = set(np.concatenate([[0], np.cumsum(sizes)]).tolist())
    rows_seen = 0
    groups = []
    for r in range(3):
        src = _FileSource(p, {}, chunk_rows=64, rank=r, nproc=3)
        chunks = [c[1] for c in src.chunks()]
        X = np.vstack(chunks) if chunks else \
            np.empty((0, full.shape[1] - 1))
        assert src.start_row == rows_seen
        # the shard's first and last rows sit ON query boundaries
        assert rows_seen in bounds and (rows_seen + len(X)) in bounds, \
            f"rank {r} shard straddles a query"
        assert int(src.group_slice.sum()) == len(X)
        np.testing.assert_allclose(
            X, full[rows_seen:rows_seen + len(X), 1:])
        groups.append(np.asarray(src.group_slice))
        rows_seen += len(X)
    assert rows_seen == len(full)
    np.testing.assert_array_equal(np.concatenate(groups), sizes)


def test_query_aligned_byte_range_empty_rank(tmp_path):
    """More ranks than queries: the starved rank reads zero bytes and an
    empty group slice instead of double-reading rows."""
    from lightgbm_tpu.dataset_io import query_aligned_byte_range

    p = str(tmp_path / "tiny.csv")
    sizes = _write_ranking_csv(p, nq=1, seed=7)
    shards = [query_aligned_byte_range(p, sizes, r, 3) for r in range(3)]
    nonempty = [s for s in shards if s[1] > s[0]]
    assert len(nonempty) == 1
    assert sum(int(np.sum(s[3])) for s in shards) == int(sizes.sum())


_CHILD_RANK_STREAM = r"""
import os, sys, json
os.environ.pop("XLA_FLAGS", None)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
try:  # cross-process CPU collectives (older jax: option absent)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
port, rank, data, out = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=rank)
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
import lightgbm_tpu as lgb
ds = lgb.Dataset(data, params={"ingest_mode": "stream",
                               "ingest_chunk_rows": 256})
bst = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                 "verbosity": -1, "min_data_in_leaf": 5,
                 "tree_learner": "data"},
                ds, num_boost_round=5)
assert ds.get_group() is not None
assert ds._dist is not None and ds._dist["nproc"] == 2
if rank == 0:
    open(out, "w").write(bst.model_to_string())
"""


@pytest.mark.slow
def test_two_process_lambdarank_streamed_matches_inmem(
        tmp_path, require_two_process_collectives):
    """Streamed distributed ranking no longer falls back (or errors) on
    .query files: chunk boundaries snap to query boundaries, and the
    2-process streamed model must match single-process INMEM training —
    structural identity implies NDCG parity, asserted explicitly."""
    data = str(tmp_path / "rank.csv")
    sizes = _write_ranking_csv(data)
    out = str(tmp_path / "dist_rank_stream_model.txt")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD_RANK_STREAM, str(port), str(r), data,
         out], env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-4000:]}"

    bst = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(data), num_boost_round=5)
    dist_model = open(out).read()
    _models_structurally_equal(bst.model_to_string(), dist_model)

    # NDCG parity vs inmem on the full file
    full = np.loadtxt(data, delimiter=",")
    y, X = full[:, 0], full[:, 1:]
    qb = np.concatenate([[0], np.cumsum(sizes)]).astype(int)
    from test_ranking import _ndcg_at
    bst_d = lgb.Booster(model_str=dist_model)
    n_in = _ndcg_at(np.asarray(bst.predict(X)), y, qb)
    n_st = _ndcg_at(np.asarray(bst_d.predict(X)), y, qb)
    assert abs(n_in - n_st) < 0.02, (n_in, n_st)


def test_shard_loading_skips_blank_and_comment_lines(tmp_path):
    """Blank/comment lines must not shift per-row sidecar alignment."""
    p = str(tmp_path / "d.csv")
    rng = np.random.RandomState(2)
    X = rng.randn(30, 3)
    y = (X[:, 0] > 0).astype(float)
    lines = [",".join(f"{v:.8f}" for v in [y[i], *X[i]]) for i in range(30)]
    lines.insert(7, "")          # blank line inside rank 0's shard
    lines.insert(20, "")
    (tmp_path / "d.csv").write_text("\n".join(lines) + "\n")
    w = rng.rand(30)
    np.savetxt(p + ".weight", w, fmt="%.8f")
    parts = [load_data_file(p, {}, rank=r, num_machines=2) for r in range(2)]
    wc = np.concatenate([q[2]["weight"] for q in parts])
    np.testing.assert_allclose(wc, w)
    np.testing.assert_allclose(np.concatenate([q[1] for q in parts]), y)


@pytest.mark.slow
def test_train_distributed_launcher(tmp_path,
                                    require_two_process_collectives):
    """lgb.train_distributed — the dask.py `_train` analog (dask.py:124-215):
    spawns local workers, shards the file by rows, trains data-parallel, and
    returns rank 0's Booster with evals_result_ attached. Must reproduce the
    single-process model structurally (same psum'd histograms)."""
    data = str(tmp_path / "train.csv")
    _write_csv(data)
    valid = str(tmp_path / "valid.csv")
    _write_csv(valid, n=800, seed=9)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "hist_backend": "stream"}
    bst = lgb.train_distributed(params, data, num_boost_round=5,
                                num_processes=2, valid_paths=[valid],
                                valid_names=["va"])
    assert bst.num_trees() == 5
    assert "va" in bst.evals_result_ and \
        len(next(iter(bst.evals_result_["va"].values()))) == 5
    ref = lgb.train(params, lgb.Dataset(data), num_boost_round=5)
    _models_structurally_equal(ref.model_to_string(), bst.model_to_string())
