"""Distributed (sharded) training tests on the 8-device CPU mesh.

Model: reference tests/distributed/_test_distributed.py (multi-process localhost
training asserting accuracy parity) — but the reference's data-/feature-parallel
learners are BIT-IDENTICAL to serial by construction (every worker applies the
same split chosen from globally reduced histograms), so these tests demand
model-string equality with the serial learner, not just accuracy.
"""
import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb

from conftest import make_synthetic_binary, make_synthetic_regression

pytestmark = pytest.mark.slow  # heavy multi-model tier (PERF.md test tiers)

needs_mesh = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _strip_params(model_str: str) -> str:
    """Model text minus the parameters block (tree_learner differs by design)."""
    return model_str.split("\nparameters:")[0]


def _assert_models_equal(a: str, b: str, exact: bool):
    """Model equality. exact=False tolerates last-ulp float drift from the
    GSPMD reduction order (structure — splits, thresholds, children, counts —
    must still match token-for-token)."""
    a, b = _strip_params(a), _strip_params(b)
    if exact:
        assert a == b
        return
    la, lb = a.splitlines(), b.splitlines()
    assert len(la) == len(lb)
    for xa, xb in zip(la, lb):
        if xa == xb:
            continue
        ka, _, va = xa.partition("=")
        kb, _, vb = xb.partition("=")
        assert ka == kb, f"{ka!r} != {kb!r}"
        if ka == "tree_sizes":    # byte lengths of the float reprs
            continue
        fa = np.array([float(t) for t in va.split()])
        fb = np.array([float(t) for t in vb.split()])
        np.testing.assert_allclose(fa, fb, rtol=3e-4, atol=3e-4,
                                   err_msg=ka)


def _datasets():
    """Three layouts: numeric+NaN, categorical+weights, EFB-bundled+weights."""
    rs = np.random.RandomState(7)
    out = []

    X, y = make_synthetic_binary(n=3000)
    X = X.copy()
    X[::13, 2] = np.nan                       # MissingType::NaN routing
    out.append(("binary_nan", {"objective": "binary"},
                dict(data=X, label=y), {}))

    Xr, yr = make_synthetic_regression(n=2500, f=8, seed=7)
    Xr = Xr.copy()
    Xr[:, 3] = rs.randint(0, 6, len(Xr))      # categorical column
    w = rs.rand(len(Xr)) + 0.5
    out.append(("reg_cat_weight", {"objective": "regression"},
                dict(data=Xr, label=yr, weight=w),
                {"categorical_feature": [3]}))

    # sparse one-hot-ish block -> EFB bundles several features per group
    Xs = np.zeros((2000, 12))
    Xs[:, :4] = rs.randn(2000, 4)
    hot = rs.randint(4, 12, 2000)
    Xs[np.arange(2000), hot] = 1.0
    ys = Xs[:, 0] + 2.0 * (hot == 5) - (hot == 9) + 0.05 * rs.randn(2000)
    ws = rs.rand(2000) + 0.5
    out.append(("reg_efb_weight", {"objective": "regression"},
                dict(data=Xs, label=ys, weight=ws), {}))
    return out


def _train(params, data_kw, ds_kw, learner, backend):
    p = dict(params, num_leaves=15, verbosity=-1, min_data_in_leaf=5,
             tree_learner=learner, hist_backend=backend)
    ds = lgb.Dataset(data_kw["data"], label=data_kw["label"],
                     weight=data_kw.get("weight"), **ds_kw)
    return lgb.train(p, ds, num_boost_round=8)


@needs_mesh
@pytest.mark.parametrize("name,params,data_kw,ds_kw", _datasets())
def test_data_parallel_bit_identical(name, params, data_kw, ds_kw):
    """tree_learner=data == serial, model-string equality (reference:
    data_parallel_tree_learner.cpp — identical splits from reduced hists)."""
    ser = _train(params, data_kw, ds_kw, "serial", "segsum")
    dat = _train(params, data_kw, ds_kw, "data", "segsum")
    _assert_models_equal(ser.model_to_string(), dat.model_to_string(),
                         exact=False)


@needs_mesh
@pytest.mark.parametrize("name,params,data_kw,ds_kw", _datasets())
def test_data_parallel_stream_bit_identical(name, params, data_kw, ds_kw):
    """The fused streaming kernel under shard_map (per-device kernel +
    histogram psum) must also reproduce the serial stream result exactly."""
    ser = _train(params, data_kw, ds_kw, "serial", "stream")
    dat = _train(params, data_kw, ds_kw, "data", "stream")
    assert dat.engine._mesh_stream
    ser_s, dat_s = ser.model_to_string(), dat.model_to_string()
    # ROOT CAUSE of the long-standing binary_nan failure (bisected in PR 6,
    # first diverging tree = tree 1, i.e. round 2): round-1 binary gradients
    # are the low-mantissa constants +-0.5 / 0.25, so every partial histogram
    # sum is exactly representable in f32 and ANY summation order gives the
    # same bits — tree 0 matches byte-for-byte below.  From round 2 the
    # gradients are sigmoid-valued with full 24-bit mantissas, and the
    # serial kernel's single-shard accumulation order differs from the
    # mesh's per-device partial sums + rank-ordered psum, so f32
    # non-associativity leaves last-ulp drift in split_gain/leaf_value
    # (~1e-5 relative; verified independent of the bf16 two-pass trick and
    # of the device count).  Structure stays token-identical; only
    # same-topology comparisons (psum vs reduce_scatter at equal D, which
    # share the per-shard partial sums) can promise full-run bit equality.
    if "weight" not in data_kw:
        t_ser, t_dat = ser_s.split("Tree="), dat_s.split("Tree=")
        assert t_ser[1] == t_dat[1], "round-1 tree must match byte-for-byte"
    _assert_models_equal(ser_s, dat_s, exact=False)


@needs_mesh
@pytest.mark.parametrize("name,params,data_kw,ds_kw", _datasets())
def test_data_parallel_reduce_scatter_bit_identical(name, params, data_kw,
                                                    ds_kw):
    """hist_comms=reduce_scatter (Reduce-Scattered histogram slices +
    shard-local split finding, docs/DISTRIBUTED.md) must reproduce the
    psum mesh path BYTE-FOR-BYTE on every training layout — psum_scatter
    slices equal the psum result restricted to the slice, and the
    shard-local scans reproduce the global scan's tie-breaks exactly."""
    dp = _train(params, data_kw, ds_kw, "data", "stream")
    p = dict(params, hist_comms="reduce_scatter")
    dr = _train(p, data_kw, ds_kw, "data", "stream")
    assert dr.engine._grow_params.hist_comms == "reduce_scatter"
    _assert_models_equal(dp.model_to_string(), dr.model_to_string(),
                         exact=True)


@needs_mesh
@pytest.mark.parametrize("name,params,data_kw,ds_kw", _datasets())
def test_feature_parallel_bit_identical(name, params, data_kw, ds_kw):
    """tree_learner=feature == serial (reference:
    feature_parallel_tree_learner.cpp — Allreduce of the best split)."""
    ser = _train(params, data_kw, ds_kw, "serial", "segsum")
    fea = _train(params, data_kw, ds_kw, "feature", "segsum")
    _assert_models_equal(ser.model_to_string(), fea.model_to_string(),
                         exact=False)


@needs_mesh
def test_explicit_mesh_shape():
    X, y = make_synthetic_regression(n=2000)
    bst = lgb.train({"objective": "regression", "verbosity": -1, "num_leaves": 15,
                     "mesh_shape": "data:8"},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < 0.6 * np.var(y)


@needs_mesh
def test_graft_dryrun_multichip():
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
