"""Distributed (sharded) training tests on the 8-device CPU mesh.

Model: reference tests/distributed/_test_distributed.py (multi-process localhost
training asserting accuracy parity) — here multi-device is native: the same grower runs
under GSPMD with rows or features sharded, so the test asserts (a) it runs, (b) quality
matches the serial learner.
"""
import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb

from conftest import make_synthetic_binary, make_synthetic_regression


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_data_parallel_matches_serial_quality():
    X, y = make_synthetic_binary(n=4000)
    p_serial = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    bst_serial = lgb.train(p_serial, lgb.Dataset(X, label=y), num_boost_round=15)
    p_data = dict(p_serial, tree_learner="data")
    bst_data = lgb.train(p_data, lgb.Dataset(X, label=y), num_boost_round=15)
    acc_s = np.mean((bst_serial.predict(X) > 0.5) == (y > 0))
    acc_d = np.mean((bst_data.predict(X) > 0.5) == (y > 0))
    assert acc_d > acc_s - 0.03, f"data-parallel {acc_d} vs serial {acc_s}"


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_feature_parallel_runs():
    X, y = make_synthetic_regression(n=2000, f=16)
    bst = lgb.train({"objective": "regression", "num_leaves": 15, "verbosity": -1,
                     "tree_learner": "feature"},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < 0.6 * np.var(y)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_explicit_mesh_shape():
    X, y = make_synthetic_regression(n=2000)
    bst = lgb.train({"objective": "regression", "verbosity": -1, "num_leaves": 15,
                     "mesh_shape": "data:8"},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < 0.6 * np.var(y)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_graft_dryrun_multichip():
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
