"""Fast-tier data-parallel comms tests (CPU mesh, every verify run).

The slow tier (test_distributed.py) proves mesh learners match SERIAL
training; this fast tier covers the comms overhaul inside the mesh path:
psum vs reduce_scatter histogram collectives must grow BYTE-IDENTICAL
models (the A/B switch `hist_comms` / env `LGBTPU_HIST_COMMS`,
docs/DISTRIBUTED.md), the telemetry comms-bytes counter must show the
~(D-1)/D payload drop, and the straggler report must split comms wait
from compute.  Runs on the conftest 8-device CPU mesh and on the 4-device
tier run_all_tests.sh adds (XLA_FLAGS=--xla_force_host_platform_device_count=4).
"""
import os

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import global_registry
from lightgbm_tpu.utils.log import LightGBMError

from conftest import make_synthetic_binary, make_synthetic_multiclass

N_DEV = len(jax.devices())
needs_mesh = pytest.mark.skipif(N_DEV < 4, reason="needs a >=4-device mesh")


def _train(params, X, y, mode, rounds=4, **ds_kw):
    p = dict(params, verbosity=-1, tree_learner="data",
             hist_backend="stream", hist_comms=mode)
    bst = lgb.train(p, lgb.Dataset(X, label=y, **ds_kw),
                    num_boost_round=rounds)
    assert bst.engine._mesh_stream
    assert bst.engine._grow_params.hist_comms == mode
    return bst


def _strip_params(model_str: str) -> str:
    """Model text minus the parameters block (hist_comms differs by design;
    every tree byte must still match)."""
    return model_str.split("\nparameters:")[0]


def _models_equal(params, X, y, rounds=4, **ds_kw):
    a = _train(params, X, y, "psum", rounds, **ds_kw)
    b = _train(params, X, y, "reduce_scatter", rounds, **ds_kw)
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())
    return b


# ---------------------------------------------------------------------------
# psum vs reduce_scatter bit-identity (the A/B switch)
# ---------------------------------------------------------------------------

@needs_mesh
def test_reduce_scatter_bit_identical_binary():
    X, y = make_synthetic_binary(n=2000, f=8)
    _models_equal({"objective": "binary", "num_leaves": 15,
                   "min_data_in_leaf": 5}, X, y)


@needs_mesh
@pytest.mark.slow
def test_reduce_scatter_bit_identical_multiclass_batched():
    """Lockstep K-class growth (grow_tree_k) on the mesh: the widened
    (K, S, G, B, 2) block reduce-scatters over its group axis and the
    K*2S-slot scan runs shard-locally — trees byte-equal to the psum
    path (and the batched path must actually engage)."""
    X, y = make_synthetic_multiclass(n=2000, f=8, k=3)
    bst = _models_equal({"objective": "multiclass", "num_class": 3,
                         "num_leaves": 11, "min_data_in_leaf": 5}, X, y,
                        rounds=3)
    assert bst.engine._mc_batched_last


@needs_mesh
def test_reduce_scatter_bit_identical_bagging():
    X, y = make_synthetic_binary(n=2000, f=8)
    _models_equal({"objective": "binary", "num_leaves": 15,
                   "min_data_in_leaf": 5, "bagging_fraction": 0.7,
                   "bagging_freq": 1, "feature_fraction": 0.8, "seed": 3},
                  X, y)


@needs_mesh
@pytest.mark.slow
def test_reduce_scatter_pipeline_chunks_bit_identical():
    """Double-buffered scatter (hist_comms_pipeline, default 2 under
    reduce_scatter): chunking the psum_scatter along the slot axis rides
    the same rank-ordered per-element reduction, so any chunk count is
    BITWISE identical to one scatter."""
    X, y = make_synthetic_binary(n=1500, f=8)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5, "tree_learner": "data",
         "hist_backend": "stream", "hist_comms": "reduce_scatter"}

    def run(chunks):
        os.environ["LGBTPU_HIST_COMMS_PIPELINE"] = str(chunks)
        try:
            bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)
        finally:
            del os.environ["LGBTPU_HIST_COMMS_PIPELINE"]
        assert bst.engine._grow_params.hist_comms_chunks == chunks
        return _strip_params(bst.model_to_string())

    assert run(1) == run(2) == run(4)


@needs_mesh
def test_reduce_scatter_env_override():
    """LGBTPU_HIST_COMMS forces the mode over the param (A/B harness)."""
    X, y = make_synthetic_binary(n=1500, f=6)
    os.environ["LGBTPU_HIST_COMMS"] = "reduce_scatter"
    try:
        p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
             "tree_learner": "data", "hist_backend": "stream",
             "hist_comms": "psum"}
        bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=2)
        assert bst.engine._grow_params.hist_comms == "reduce_scatter"
    finally:
        del os.environ["LGBTPU_HIST_COMMS"]


@needs_mesh
def test_reduce_scatter_constraint_fallback():
    """Constraint features fall back to psum (logged, still trains)."""
    X, y = make_synthetic_binary(n=1500, f=6)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "tree_learner": "data", "hist_backend": "stream",
         "hist_comms": "reduce_scatter",
         "monotone_constraints": [1] + [0] * 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=2)
    assert bst.engine._grow_params.hist_comms == "psum"


def test_hist_comms_validation():
    X, y = make_synthetic_binary(n=500, f=4)
    with pytest.raises(LightGBMError, match="hist_comms"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "hist_comms": "allreduce"},
                  lgb.Dataset(X, label=y), num_boost_round=1)
    with pytest.raises(LightGBMError, match="hist_comms_dtype"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "hist_comms_dtype": "fp8"},
                  lgb.Dataset(X, label=y), num_boost_round=1)


@needs_mesh
def test_bf16_pair_compressed_comms_trains():
    """Opt-in compressed wire payload: not bit-identical to psum, but the
    model must stay accurate (the quantized-training tolerance claim)."""
    X, y = make_synthetic_binary(n=2000, f=8)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5, "tree_learner": "data",
         "hist_backend": "stream", "hist_comms": "reduce_scatter",
         "hist_comms_dtype": "bf16_pair"}
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    assert bst.engine._grow_params.hist_comms_dtype == "bf16_pair"
    acc = np.mean((np.asarray(bst.predict(X)) > 0.5) == y)
    ref = lgb.train(dict(p, hist_comms_dtype="f32"),
                    lgb.Dataset(X, label=y), num_boost_round=4)
    acc_ref = np.mean((np.asarray(ref.predict(X)) > 0.5) == y)
    # quantize-once wire compression must not cost meaningful quality
    assert acc >= acc_ref - 0.02


# ---------------------------------------------------------------------------
# telemetry: comms-bytes counter + straggler comms/compute split
# ---------------------------------------------------------------------------

@needs_mesh
def test_comms_bytes_counter_drop():
    """The per-round histogram payload drops ~(D-1)/D in reduce_scatter
    mode (delivered-payload convention, docs/DISTRIBUTED.md): full block
    vs one G/D group slice — exactly G / ceil(G/D) minus the tiny
    best-record payload (= D when D divides the group count, as with
    these 8 unbundled features on the 4/8-device meshes)."""
    X, y = make_synthetic_binary(n=1500, f=8)

    def per_round(mode):
        global_registry.reset()
        p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
             "tree_learner": "data", "hist_backend": "stream",
             "hist_comms": mode, "telemetry": True}
        bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=2)
        snap = global_registry.snapshot()
        assert snap["counters"]["comms/hist_bytes"] > 0
        recs = [r for r in global_registry.records
                if r.get("event") == "iteration"]
        assert recs[-1]["comms_mode"] == mode
        assert recs[-1]["comms_bytes"] > 0
        return snap["gauges"]["comms/hist_bytes_per_round"], bst

    b_psum, bst = per_round("psum")
    b_rs, _ = per_round("reduce_scatter")
    g = bst.engine.dd.num_groups
    expected = g / -(-g // N_DEV)      # delivered drop: full G vs G/D slice
    ratio = b_psum / b_rs
    assert ratio > 0.8 * expected
    assert ratio <= expected + 1e-6


def test_straggler_report_splits_comms_from_compute():
    from lightgbm_tpu.parallel.straggler import straggler_report

    # slow DEVICE: host 2's compute mean is 2x the median, others idle at
    # the barrier — classification must blame the device
    stats = np.array([[50, 0.10, 0.12, 0.05],
                      [50, 0.10, 0.11, 0.05],
                      [50, 0.20, 0.25, 0.00],
                      [50, 0.10, 0.12, 0.05]])
    rep = straggler_report([], _all_host_stats=stats)
    assert rep["bottleneck"] == "device"
    assert rep["slowest_host"] == 2
    assert rep["median_comms_wait_s"] == 0.05

    # slow LINK: level compute, everyone waits at the barrier
    stats = np.array([[50, 0.10, 0.11, 0.08],
                      [50, 0.10, 0.11, 0.09],
                      [50, 0.11, 0.12, 0.08],
                      [50, 0.10, 0.11, 0.08]])
    rep = straggler_report([], _all_host_stats=stats)
    assert rep["bottleneck"] == "link"

    # balanced: neither skew nor wait
    stats = np.array([[50, 0.10, 0.11, 0.001],
                      [50, 0.10, 0.11, 0.001]])
    rep = straggler_report([], _all_host_stats=stats)
    assert rep["bottleneck"] == "balanced"

    # legacy 3-column test rows still work (comms columns default to 0)
    stats = np.array([[50, 0.10, 0.11], [50, 0.30, 0.35]])
    rep = straggler_report([], _all_host_stats=stats)
    assert rep["bottleneck"] == "device"
    assert rep["median_comms_wait_s"] == 0.0

    # DISPATCH-bound: level compute, no barrier wait, but the eager
    # pipeline's many launches/host-syncs per iteration (6-column rows:
    # [n, mean, max, comms_mean, launches/iter, syncs/iter])
    stats = np.array([[50, 0.10, 0.11, 0.001, 9.0, 3.0],
                      [50, 0.10, 0.11, 0.001, 9.0, 3.0]])
    rep = straggler_report([], _all_host_stats=stats)
    assert rep["bottleneck"] == "dispatch"
    assert rep["launches_per_iter"] == 9.0

    # the fused one-launch path reads BALANCED on the same compute
    stats = np.array([[50, 0.10, 0.11, 0.001, 1.0, 0.1],
                      [50, 0.10, 0.11, 0.001, 1.0, 0.1]])
    rep = straggler_report([], _all_host_stats=stats)
    assert rep["bottleneck"] == "balanced"
    assert rep["host_syncs_per_iter"] == 0.1


# ---------------------------------------------------------------------------
# parse_mesh_shape validation (parallel/mesh.py)
# ---------------------------------------------------------------------------

def test_parse_mesh_shape_valid():
    from lightgbm_tpu.parallel.mesh import parse_mesh_shape
    assert parse_mesh_shape("data:4") == (("data",), (4,))
    assert parse_mesh_shape(" data:4 , feature:2 ") == \
        (("data", "feature"), (4, 2))


@pytest.mark.parametrize("spec", [
    "data:",          # empty size (used to raise a bare ValueError)
    "data:x",         # non-integer size
    "data:0",         # non-positive size
    "data:-2",
    "data:4,data:2",  # duplicate axis name
    "data",           # no separator
    ":4",             # empty axis name
    ",",              # no axes at all
])
def test_parse_mesh_shape_invalid(spec):
    from lightgbm_tpu.parallel.mesh import parse_mesh_shape
    with pytest.raises(LightGBMError):
        parse_mesh_shape(spec)
