"""End-to-end training tests (model: reference tests/python_package_test/test_engine.py)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import (make_synthetic_binary, make_synthetic_multiclass,
                      make_synthetic_regression)


def _split(X, y, frac=0.2, seed=1):
    rs = np.random.RandomState(seed)
    n = len(y)
    test = rs.rand(n) < frac
    return X[~test], y[~test], X[test], y[test]


def test_regression_l2():
    X, y = make_synthetic_regression()
    Xtr, ytr, Xte, yte = _split(X, y)
    train_set = lgb.Dataset(Xtr, label=ytr)
    bst = lgb.train({"objective": "regression", "num_leaves": 31, "verbosity": -1,
                     "learning_rate": 0.1}, train_set, num_boost_round=50)
    pred = bst.predict(Xte)
    mse = float(np.mean((pred - yte) ** 2))
    base = float(np.var(yte))
    assert mse < 0.35 * base, f"mse {mse} vs var {base}"


def test_binary_classification():
    X, y = make_synthetic_binary()
    Xtr, ytr, Xte, yte = _split(X, y)
    train_set = lgb.Dataset(Xtr, label=ytr)
    bst = lgb.train({"objective": "binary", "num_leaves": 31, "verbosity": -1},
                    train_set, num_boost_round=50)
    p = bst.predict(Xte)
    assert p.min() >= 0 and p.max() <= 1
    acc = np.mean((p > 0.5) == (yte > 0))
    assert acc > 0.72, f"accuracy {acc}"


@pytest.mark.slow
def test_binary_auc_improves():
    X, y = make_synthetic_binary(n=4000)
    Xtr, ytr, Xte, yte = _split(X, y)
    train_set = lgb.Dataset(Xtr, label=ytr)
    valid_set = train_set.create_valid(Xte, label=yte)
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": ["auc", "binary_logloss"],
                     "num_leaves": 31, "verbosity": -1},
                    train_set, num_boost_round=60, valid_sets=[valid_set],
                    callbacks=[lgb.record_evaluation(evals)])
    aucs = evals["valid_0"]["auc"]
    assert aucs[-1] > 0.78
    assert aucs[-1] > aucs[0]


@pytest.mark.slow
def test_multiclass():
    X, y = make_synthetic_multiclass()
    Xtr, ytr, Xte, yte = _split(X, y)
    train_set = lgb.Dataset(Xtr, label=ytr)
    bst = lgb.train({"objective": "multiclass", "num_class": 4, "verbosity": -1,
                     "num_leaves": 15}, train_set, num_boost_round=30)
    p = bst.predict(Xte)
    assert p.shape == (len(yte), 4)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    acc = np.mean(np.argmax(p, axis=1) == yte)
    assert acc > 0.75, f"accuracy {acc}"


@pytest.mark.slow
def test_early_stopping():
    X, y = make_synthetic_regression()
    Xtr, ytr, Xte, yte = _split(X, y)
    train_set = lgb.Dataset(Xtr, label=ytr)
    valid_set = train_set.create_valid(Xte, label=yte)
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "early_stopping_round": 5},
                    train_set, num_boost_round=500, valid_sets=[valid_set])
    assert bst.best_iteration > 0
    assert bst.best_iteration < 500


def test_model_save_load_roundtrip(tmp_path):
    X, y = make_synthetic_binary()
    train_set = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 15},
                    train_set, num_boost_round=10)
    pred1 = bst.predict(X, raw_score=True)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    pred2 = bst2.predict(X, raw_score=True)
    np.testing.assert_allclose(pred1, pred2, rtol=1e-5, atol=1e-6)
    # probabilities too (objective string round-trips)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-5, atol=1e-6)


def test_bagging_and_feature_fraction():
    X, y = make_synthetic_regression(n=3000)
    train_set = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "bagging_fraction": 0.7, "bagging_freq": 1,
                     "feature_fraction": 0.8}, train_set, num_boost_round=30)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < np.var(y)


def test_goss():
    X, y = make_synthetic_binary(n=3000)
    train_set = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "data_sample_strategy": "goss"}, train_set, num_boost_round=40)
    p = bst.predict(X)
    acc = np.mean((p > 0.5) == (y > 0))
    assert acc > 0.78


@pytest.mark.slow
def test_dart():
    X, y = make_synthetic_regression()
    train_set = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "boosting": "dart",
                     "verbosity": -1}, train_set, num_boost_round=30)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < 0.6 * np.var(y)


def test_rf():
    X, y = make_synthetic_binary(n=3000)
    train_set = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "rf",
                     "bagging_fraction": 0.7, "bagging_freq": 1,
                     "verbosity": -1}, train_set, num_boost_round=20)
    p = bst.predict(X)
    acc = np.mean((p > 0.5) == (y > 0))
    assert acc > 0.78


@pytest.mark.slow
def test_l1_objective_renews_leaves():
    X, y = make_synthetic_regression()
    train_set = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression_l1", "verbosity": -1},
                    train_set, num_boost_round=40)
    pred = bst.predict(X)
    mae = np.mean(np.abs(pred - y))
    assert mae < 0.6 * np.mean(np.abs(y - np.median(y)))


def test_categorical_features():
    rs = np.random.RandomState(7)
    n = 3000
    cat = rs.randint(0, 8, n)
    x1 = rs.randn(n)
    effect = np.array([2.0, -1.0, 0.5, 3.0, -2.0, 0.0, 1.0, -0.5])
    y = effect[cat] + 0.5 * x1 + 0.1 * rs.randn(n)
    X = np.column_stack([cat.astype(np.float64), x1])
    train_set = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "regression", "verbosity": -1, "num_leaves": 15,
                     "min_data_per_group": 10},
                    train_set, num_boost_round=40)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < 0.1 * np.var(y)


def test_custom_objective():
    X, y = make_synthetic_regression()
    train_set = lgb.Dataset(X, label=y)

    def fobj(preds, dataset):
        grad = preds - dataset.get_label()
        hess = np.ones_like(grad)
        return grad, hess

    # custom objective via booster.update
    bst2 = lgb.Booster({"objective": "none", "verbosity": -1},
                       lgb.Dataset(X, label=y))
    for _ in range(30):
        bst2.update(fobj=fobj)
    pred = bst2.predict(X, raw_score=True)
    assert np.mean((pred - y) ** 2) < 0.5 * np.var(y)


def test_feature_importance():
    X, y = make_synthetic_regression()
    train_set = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "verbosity": -1}, train_set,
                    num_boost_round=20)
    imp_split = bst.feature_importance("split")
    imp_gain = bst.feature_importance("gain")
    assert imp_split.shape == (X.shape[1],)
    assert imp_split.sum() > 0
    # informative features should dominate
    assert imp_gain[0] > imp_gain[5]


def test_valid_names_length_mismatch_raises():
    X, y = make_synthetic_regression()
    train_set = lgb.Dataset(X, label=y)
    vs = lgb.Dataset(X[:200], label=y[:200], reference=train_set)
    with pytest.raises(lgb.LightGBMError):
        lgb.train({"objective": "regression", "verbosity": -1}, train_set,
                  num_boost_round=2, valid_sets=[vs, vs], valid_names=["only_one"])


def test_trivial_tree_walk_resolves_leaf0():
    # trivial tree (num_leaves=1, zero-filled children) must resolve every row to
    # leaf 0, not gather padding at leaf_value[-1]
    import jax.numpy as jnp
    from lightgbm_tpu.ops.predict import _walk_one_tree

    X, y = make_synthetic_regression(n=300)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    dd = ds.device_data()
    L = 8
    Bmax = dd.max_bins
    zeros = jnp.zeros(L, jnp.int32)
    fields = (zeros, zeros, zeros, zeros, zeros, jnp.zeros((L, Bmax), bool))
    leaf = _walk_one_tree(fields, dd.bins, dd.routing, L)
    assert int(jnp.max(leaf)) == 0 and int(jnp.min(leaf)) == 0


def test_no_trailing_trivial_trees():
    """When growth stops, splitless zero trees appended between the delayed
    finished-flag polls are dropped (reference: gbdt.cpp stops without
    keeping them)."""
    rng = np.random.RandomState(3)
    X = rng.randn(200, 3)
    y = (X[:, 0] > 0).astype(np.float64)  # one split fits it exactly
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster({"objective": "regression", "num_leaves": 4,
                       "learning_rate": 1.0, "verbosity": -1,
                       "min_gain_to_split": 1e-3,
                       "min_data_in_leaf": 1}, ds)
    # emulate the TPU's deferred device->host poll cadence
    bst.engine._finished_check_every = 8
    finished_at = None
    for i in range(30):
        if bst.update():
            finished_at = i
            break
    assert finished_at is not None
    trees = bst.engine.models
    # the trailing single-leaf zero trees between polls were trimmed
    assert bst.num_trees() < finished_at + 1
    assert trees[-1].num_leaves > 1
    assert bst.engine.iter_ == bst.num_trees()


def test_fused_iteration_matches_unfused():
    """The whole-iteration fused program (gradients -> grow -> score update
    as one launch) must reproduce the step-by-step path to float tolerance."""
    import os
    rs = np.random.RandomState(11)
    X = rs.randn(2000, 8)
    y = (X[:, 0] - X[:, 1] + 0.3 * rs.randn(2000) > 0).astype(float)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5}
    os.environ["LGBTPU_FUSE_ITER"] = "1"
    try:
        fused = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=8)
        assert fused.engine._iter_fn is not None, "fused path did not engage"
    finally:
        os.environ["LGBTPU_FUSE_ITER"] = "0"
        plain = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=8)
        del os.environ["LGBTPU_FUSE_ITER"]
    np.testing.assert_allclose(fused.predict(X), plain.predict(X),
                               rtol=1e-4, atol=1e-5)
