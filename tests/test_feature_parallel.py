"""Feature-parallel learner (tree_learner=feature) on the CPU mesh.

Reference: src/treelearner/feature_parallel_tree_learner.cpp:25-83 — every
worker scans only its own feature subset and the workers Allreduce nothing
but SplitInfo records.  Here bins is sharded over its feature-GROUP axis,
each device builds histograms and runs the full split scan over ONLY its
G/D group slice (parallel/comms.py ShardPlan sub-layouts), and the 7-field
per-shard best records are all_gathered with the exact (max gain, lowest
global feature id) tie-break — ZERO histogram bytes cross the wire.

Discipline (docs/DISTRIBUTED.md): trees BYTE-IDENTICAL to the serial
learner across the layout matrix with the fused path off; the fused
one-launch path proves itself with the PR 10 round-1-byte + structural
ulp identity.  Runs on the conftest 8-device CPU mesh and on the 4-device
tier run_all_tests.sh adds.
"""
import os

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import host_sync_count, launch_count
from lightgbm_tpu.utils.log import LightGBMError

from conftest import (make_synthetic_binary, make_synthetic_multiclass,
                      make_synthetic_regression)

N_DEV = len(jax.devices())
MESHES = [d for d in (4, 8) if d <= N_DEV]
needs_mesh = pytest.mark.skipif(N_DEV < 4, reason="needs a >=4-device mesh")


def _strip_params(model_str: str) -> str:
    return model_str.split("\nparameters:")[0]


def _set_env(name, value):
    """Set/unset an env var, returning a restore callable that puts the
    PRIOR value back (a bare del would clobber a caller's export)."""
    prior = os.environ.get(name)
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = value

    def restore():
        if prior is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prior
    return restore


def _train(params, X, y, learner, rounds=4, mesh_dev=None, fuse="0",
           **ds_kw):
    p = dict(params, verbosity=-1, tree_learner=learner)
    if mesh_dev and learner == "feature":
        p["mesh_shape"] = f"feature:{mesh_dev}"
    restore = _set_env("LGBTPU_FUSE_ITER", fuse) if fuse is not None \
        else (lambda: None)
    try:
        return lgb.train(p, lgb.Dataset(X, label=y, **ds_kw),
                         num_boost_round=rounds)
    finally:
        restore()


def _assert_serial_identity(params, X, y, rounds=4, mesh_dev=None, **ds_kw):
    """Feature-parallel trees must match the serial learner BYTE-for-byte
    (fused off on both arms so the gradient programs are identical)."""
    s = _train(params, X, y, "serial", rounds, **ds_kw)
    f = _train(params, X, y, "feature", rounds, mesh_dev=mesh_dev, **ds_kw)
    assert f.engine._feature_mode, "feature learner should be active"
    assert _strip_params(s.model_to_string()) == \
        _strip_params(f.model_to_string())
    return f


# ---------------------------------------------------------------------------
# layout matrix: byte identity vs serial at 4- and 8-way meshes
# ---------------------------------------------------------------------------

def _layouts():
    """numeric+NaN, categorical, EFB-bundled, weighted — the distributed
    layout matrix (mirrors tests/test_distributed._datasets)."""
    rs = np.random.RandomState(7)
    out = []
    X, y = make_synthetic_binary(n=3000)
    X = X.copy()
    X[::13, 2] = np.nan
    out.append(("binary_nan", {"objective": "binary"},
                dict(data=X, label=y), {}))
    Xr, yr = make_synthetic_regression(n=2500, f=8, seed=7)
    Xr = Xr.copy()
    Xr[:, 3] = rs.randint(0, 6, len(Xr))
    w = rs.rand(len(Xr)) + 0.5
    out.append(("reg_cat_weight", {"objective": "regression"},
                dict(data=Xr, label=yr, weight=w),
                {"categorical_feature": [3]}))
    Xs = np.zeros((2000, 12))
    Xs[:, :4] = rs.randn(2000, 4)
    hot = rs.randint(4, 12, 2000)
    Xs[np.arange(2000), hot] = 1.0
    ys = Xs[:, 0] + 2.0 * (hot == 5) - (hot == 9) + 0.05 * rs.randn(2000)
    out.append(("reg_efb", {"objective": "regression"},
                dict(data=Xs, label=ys), {}))
    return out


@needs_mesh
@pytest.mark.parametrize("mesh_dev", MESHES)
@pytest.mark.parametrize("name,params,data_kw,ds_kw",
                         _layouts(), ids=[t[0] for t in _layouts()])
def test_feature_parallel_bit_identical(name, params, data_kw, ds_kw,
                                        mesh_dev):
    p = dict(params, num_leaves=15, min_data_in_leaf=5)
    _assert_serial_identity(p, data_kw["data"], data_kw["label"],
                            mesh_dev=mesh_dev,
                            weight=data_kw.get("weight"), **ds_kw)


@needs_mesh
def test_feature_parallel_multiclass_bit_identical():
    """K class trees ride the per-class lax.scan (one launch) under the
    feature mesh and stay byte-identical to serial."""
    X, y = make_synthetic_multiclass(n=2000, f=8, k=3)
    _assert_serial_identity({"objective": "multiclass", "num_class": 3,
                             "num_leaves": 11, "min_data_in_leaf": 5},
                            X, y, rounds=3)


@needs_mesh
def test_feature_parallel_feature_fraction_identical():
    """The tree-level column mask rides the replicated col_mask into every
    shard-local scan — same RNG draw, same trees."""
    X, y = make_synthetic_binary(n=2000, f=10)
    _assert_serial_identity({"objective": "binary", "num_leaves": 15,
                             "min_data_in_leaf": 5,
                             "feature_fraction": 0.6, "seed": 3}, X, y)


@needs_mesh
def test_feature_parallel_goss_compaction_identical():
    """GOSS row compaction under the feature mesh: rows are replicated, so
    the stable-partition compact view is single-device-shaped; any
    covering capacity grows the identical tree."""
    X, y = make_synthetic_binary(n=4000, f=8)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "data_sample_strategy": "goss", "learning_rate": 0.5,
         "top_rate": 0.1, "other_rate": 0.15}
    restore = _set_env("LGBTPU_COMPACT", "off")
    try:
        off = _train(p, X, y, "feature", rounds=6)
    finally:
        restore()
    on = _train(p, X, y, "feature", rounds=6)
    assert on.engine._last_compact_rows > 0, "compaction never engaged"
    assert _strip_params(off.model_to_string()) == \
        _strip_params(on.model_to_string())
    # and the compacted run still matches serial byte-for-byte
    s = _train(p, X, y, "serial", rounds=6)
    assert _strip_params(s.model_to_string()) == \
        _strip_params(on.model_to_string())


# ---------------------------------------------------------------------------
# fused one-launch path
# ---------------------------------------------------------------------------

@needs_mesh
def test_feature_parallel_fused_identity():
    """Fused (default) vs unfused: round-1 tree byte-equal, later rounds
    structurally identical with ulp float tolerance (the PR 10
    non-associativity discipline — XLA re-fuses the wider program's
    gradient chain)."""
    from tests.test_fused_sharded import _assert_fused_identity

    X, y = make_synthetic_binary(n=2000, f=8)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5}
    f = _train(p, X, y, "feature", fuse=None)
    assert f.engine._fused_last, "fused path did not engage by default"
    u = _train(p, X, y, "feature", fuse="0")
    assert not u.engine._fused_last
    _assert_fused_identity(f.model_to_string(), u.model_to_string())


@needs_mesh
def test_feature_parallel_single_launch_zero_syncs():
    """The acceptance contract: <= 1 jitted launch per boosting iteration
    and 0 host syncs/iter on the fused feature-parallel path."""
    X, y = make_synthetic_binary(n=2000, f=8)
    bst = _train({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 5}, X, y, "feature", rounds=2,
                 fuse=None)
    l0, s0 = launch_count(), host_sync_count()
    for _ in range(4):
        bst.update()
    assert (launch_count() - l0) / 4 <= 1.5
    assert (host_sync_count() - s0) / 4 == 0.0


@needs_mesh
def test_feature_parallel_state_replicated():
    """Satellite contract (ISSUE 15): every per-row array — score, grad,
    hess, mask, leaf routing — is pinned fully REPLICATED across the
    feature mesh, and the fused state keeps that placement."""
    X, y = make_synthetic_binary(n=2000, f=8)
    bst = _train({"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 5}, X, y, "feature", fuse=None)
    eng = bst.engine
    assert eng.score.sharding.is_fully_replicated
    st = eng._train_state
    assert st is not None and st.score is eng.score
    for name in ("score", "grad", "hess", "leaf_id", "mask"):
        arr = getattr(st, name)
        assert arr.sharding.is_fully_replicated, \
            f"state.{name} lost replication: {arr.sharding}"
    # bins stays sharded over its GROUP axis
    spec = tuple(eng.dd.bins.sharding.spec)
    assert eng._feature_axis in spec


# ---------------------------------------------------------------------------
# comms accounting: zero histogram payload
# ---------------------------------------------------------------------------

@needs_mesh
def test_feature_parallel_zero_hist_bytes():
    """comms/hist_bytes carries ONLY split-record traffic: the analytic
    histogram-column payload is exactly 0 and the per-round record bytes
    are orders of magnitude below the data-parallel block."""
    from lightgbm_tpu.telemetry import global_registry

    X, y = make_synthetic_binary(n=1500, f=8)
    global_registry.reset()
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "tree_learner": "feature",
                     "telemetry": True},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    cm = bst.engine._comms_model()
    assert cm["mode"] == "feature"
    assert cm["hist_block_bytes"] == 0
    recs = [r for r in global_registry.records
            if r.get("event") == "iteration"]
    assert recs[-1]["comms_mode"] == "feature"
    # record payload: 7 fields x 4 bytes x slots x shards (+ cat bitsets)
    from lightgbm_tpu.parallel.comms import hist_comms_bytes_per_round
    gp = bst.engine._grow_params
    S = min(gp.max_splits_per_round, max(gp.num_leaves - 1, 1))
    d = cm["devices"]
    psum_block = hist_comms_bytes_per_round(
        2 * S, bst.engine.dd.num_groups, bst.engine.dd.max_bins, d, "psum")
    assert cm["per_round_bytes"] * 20 < psum_block


# ---------------------------------------------------------------------------
# validation: loud errors instead of silent fallthrough
# ---------------------------------------------------------------------------

def test_combined_mesh_rejected():
    """data:X,feature:Y combined meshes stay rejected for every learner
    EXCEPT tree_learner=data, which now consumes both axes as the 2D
    rows x feature-groups mesh (tests/test_mesh2d.py); the refusal names
    the supported 2D spelling instead of claiming 2-axis sharding is
    unsupported."""
    X, y = make_synthetic_binary(n=500, f=4)
    for learner in ({}, {"tree_learner": "feature"},
                    {"tree_learner": "voting"}):
        with pytest.raises(LightGBMError, match="2-axis") as ei:
            lgb.train(dict({"objective": "binary", "verbosity": -1,
                            "mesh_shape": "data:2,feature:2"}, **learner),
                      lgb.Dataset(X, label=y), num_boost_round=1)
        assert "tree_learner=data" in str(ei.value)


@needs_mesh
def test_feature_learner_needs_feature_axis():
    X, y = make_synthetic_binary(n=500, f=4)
    with pytest.raises(LightGBMError, match="feature"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "tree_learner": "feature", "mesh_shape": "data:4"},
                  lgb.Dataset(X, label=y), num_boost_round=1)


@needs_mesh
def test_feature_learner_rejects_constraints():
    X, y = make_synthetic_binary(n=500, f=4)
    with pytest.raises(LightGBMError, match="tree_learner=feature"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "tree_learner": "feature",
                   "monotone_constraints": [1, 0, 0, 0]},
                  lgb.Dataset(X, label=y), num_boost_round=1)


@needs_mesh
def test_feature_learner_rejects_stream_backend():
    X, y = make_synthetic_binary(n=500, f=4)
    with pytest.raises(LightGBMError):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "tree_learner": "feature", "hist_backend": "stream"},
                  lgb.Dataset(X, label=y), num_boost_round=1)


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

@needs_mesh
def test_feature_parallel_checkpoint_resume(tmp_path):
    """A mid-run snapshot resumes BIT-IDENTICALLY under the feature mesh
    (same discipline as the data-parallel sharded-state resume suite)."""
    X, y = make_synthetic_binary(n=2000, f=8)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tree_learner": "feature", "min_data_in_leaf": 5,
         "snapshot_freq": 3, "snapshot_keep": 8}
    out = str(tmp_path / "model.txt")
    full = lgb.train(dict(p, output_model=out), lgb.Dataset(X, label=y),
                     num_boost_round=6)
    snap = out + ".snapshot_iter_3"
    assert os.path.exists(snap)
    resumed = lgb.train(dict(p, resume_from=snap, output_model=out),
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert _strip_params(full.model_to_string()) == \
        _strip_params(resumed.model_to_string())
