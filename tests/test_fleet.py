"""Serving-fleet resilience (docs/SERVING.md "Fleet architecture").

The contract under test:

  * deadline propagation: an expired request is shed at admission AND at
    the batcher's pre-dispatch check — the device never scores a request
    whose client gave up;
  * overload shedding stays BOUNDED under a 10x burst and every shed 503
    carries ``Retry-After`` + the structured reason;
  * the circuit breaker walks closed -> open -> half-open -> closed
    deterministically, and the fanout front routes around a dead
    replica without surfacing client errors;
  * the fleet supervisor restarts killed replicas (with backoff) while
    traffic keeps flowing through the front;
  * fleet-wide promotion through the shared pointer: a poisoned
    candidate is rejected by every replica's re-validation — the fleet
    keeps serving its old version and surfaces degraded state — while a
    valid candidate converges everywhere, including on replicas
    restarted mid-promotion.
"""
import json
import os
import signal
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (CircuitBreaker, DeadlineError,
                                  FanoutFront, MicroBatcher, ModelRegistry,
                                  OverloadError, ServingApp, ServingFleet,
                                  reuseport_available)
from lightgbm_tpu.serving.fleet import (promote_pointer, read_pointer,
                                        validate_candidate)
from lightgbm_tpu.serving.front import http_json


def _make_data(seed=7, n=400):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    y = ((X[:, 1] > 0) ^ (X[:, 2] > 0.5)).astype(np.float64)
    return X, y


def _train_to_file(path, seed=3, num_boost_round=4):
    X, y = _make_data()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5, "seed": seed},
                    lgb.Dataset(X, label=y),
                    num_boost_round=num_boost_round)
    bst.save_model(str(path))
    return X


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    td = tmp_path_factory.mktemp("fleet")
    pa, pb = td / "model_a.txt", td / "model_b.txt"
    X = _train_to_file(pa, seed=3)
    _train_to_file(pb, seed=11, num_boost_round=6)
    return (str(pa), str(pb), X,
            lgb.Booster(model_file=str(pa)), lgb.Booster(model_file=str(pb)))


# ---------------------------------------------------------------------------
# deadline propagation (batcher level: never reaches the device)
# ---------------------------------------------------------------------------

def test_deadline_expired_at_admission(served):
    pa, _, X, _, _ = served
    b = MicroBatcher(ModelRegistry(pa), max_delay_ms=1.0)
    with pytest.raises(DeadlineError) as ei:
        b.submit(X[:2], deadline=time.perf_counter() - 0.01)
    payload = ei.value.payload()
    assert payload["error"] == "deadline_expired"
    assert payload["retry_after_s"] == 0.0
    assert b.expired == 1 and b.batches == 0


def test_deadline_expired_in_queue_never_dispatches(served):
    """Requests whose budget lapses while queued are expired by the
    worker WITHOUT a device dispatch (zero batches processed)."""
    pa, _, X, _, _ = served
    b = MicroBatcher(ModelRegistry(pa), max_delay_ms=1.0)   # worker OFF
    futs = [b.submit(X[i:i + 2], deadline=time.perf_counter() + 0.05)
            for i in range(3)]
    time.sleep(0.15)          # all three budgets lapse while queued
    b.start()
    for f in futs:
        with pytest.raises(DeadlineError):
            f.result(timeout=5)
    b.stop()
    assert b.batches == 0     # nothing reached the model/device
    assert b.expired == 3


def test_live_deadline_still_served(served):
    pa, _, X, ref, _ = served
    b = MicroBatcher(ModelRegistry(pa), max_delay_ms=1.0).start()
    try:
        res = b.submit(X[:3], raw_score=True,
                       deadline=time.perf_counter() + 10.0).result(timeout=5)
        assert np.array_equal(res.values, ref.predict(X[:3], raw_score=True))
    finally:
        b.stop()


# ---------------------------------------------------------------------------
# overload: bounded queue under burst, structured Retry-After
# ---------------------------------------------------------------------------

def test_burst_10x_queue_stays_bounded(served):
    """Satellite regression: a burst of 10x serve_queue_size requests
    must shed the overflow at the door — the queue depth never exceeds
    its bound and every rejection is a structured Retry-After 503."""
    pa, _, X, _, _ = served
    qs = 16
    b = MicroBatcher(ModelRegistry(pa), queue_size=qs,
                     max_delay_ms=1.0)    # worker OFF: pure admission
    admitted, shed = 0, 0
    for i in range(10 * qs):
        try:
            b.submit(X[:1])
            admitted += 1
        except OverloadError as e:
            shed += 1
            payload = e.payload()
            assert payload["error"] == "overload"
            assert payload["reason"] == "queue_full"
            assert payload["queue_depth"] <= qs
            assert payload["retry_after_s"] > 0
        assert b.queue_depth() <= qs      # bounded THROUGHOUT the burst
    assert admitted == qs and shed == 9 * qs
    assert b.rejected == shed
    b.start()
    b.stop(drain=True)                    # admitted requests still serve
    assert b.served == admitted


def test_server_retry_after_header_and_ready(served):
    pa, _, X, _, _ = served
    app = ServingApp(pa, port=0, max_batch=16, max_delay_ms=1.0).start()
    try:
        # readiness: up + model loaded -> 200 with routing fields
        st, obj, _ = http_json(app.host, app.port, "GET", "/ready",
                               timeout=5)
        assert st == 200 and obj["ready"]
        assert obj["queue_depth"] == 0 and obj["model_version"] == 1
        assert "model_sha256" in obj
        # liveness stays its own endpoint
        st, obj, _ = http_json(app.host, app.port, "GET", "/health",
                               timeout=5)
        assert st == 200 and obj["status"] == "ok"
        # a pre-expired budget is shed with the structured 503 + header
        st, obj, headers = http_json(
            app.host, app.port, "POST", "/predict",
            {"rows": X[:2].tolist(), "deadline_ms": 1e-6}, timeout=5)
        assert st == 503
        assert obj["error"] == "deadline_expired"
        assert "Retry-After" in headers
    finally:
        app.shutdown()
    # draining flips readiness off
    assert app.draining


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def test_circuit_breaker_trip_halfopen_recover():
    clock = [0.0]
    br = CircuitBreaker(failures=3, cooldown_s=5.0, clock=lambda: clock[0])
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"           # under threshold
    br.record_failure()                   # 3rd consecutive: trip
    assert br.state == "open" and br.trips == 1
    assert not br.allow()
    clock[0] = 4.9
    assert not br.allow()                 # still cooling down
    clock[0] = 5.1
    assert br.state == "half_open"
    assert br.allow()                     # ONE probe claims the slot
    assert not br.allow() and not br.peek()
    br.record_failure()                   # failed probe: re-open
    assert br.state == "open" and br.trips == 2
    clock[0] = 10.3
    assert br.allow()                     # next probe
    br.record_success()                   # probe succeeded: close
    assert br.state == "closed" and br.allow()
    assert br.describe()["consecutive_failures"] == 0


def test_circuit_breaker_success_resets_count():
    br = CircuitBreaker(failures=3)
    br.record_failure()
    br.record_failure()
    br.record_success()                   # consecutive means consecutive
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"


class _StubFleet:
    """Just enough fleet for a FanoutFront: a static endpoint table."""

    def __init__(self, eps):
        self._eps = dict(eps)
        self.replicas = len(eps)

    def endpoints(self):
        return dict(self._eps)

    @property
    def generation(self):
        return 1

    def describe(self, states=None):
        return {"stub": True}


class _FlakyReplica:
    """Answers /ready 200 but resets every /predict connection — a
    replica crashing mid-request, the case readiness polling alone
    cannot catch (only the breaker can)."""

    def __init__(self):
        import socket
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                body = json.dumps({"ready": True, "queue_depth": 0,
                                   "model_version": 1}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        import threading
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def port(self):
        return self.httpd.server_address[1]

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_front_routes_around_dead_replica(served):
    """One live replica + one that resets every /predict: every request
    lands 200 on the live one; the flaky rank's breaker trips open and
    stops eating attempts; client-visible errors stay zero."""
    pa, _, X, ref, _ = served
    app = ServingApp(pa, port=0, max_batch=16, max_delay_ms=1.0).start()
    flaky = _FlakyReplica()
    fleet = _StubFleet({0: {"host": "127.0.0.1", "port": flaky.port},
                        1: {"host": app.host, "port": app.port}})
    front = FanoutFront(fleet, port=0, retries=2, retry_backoff_ms=1.0,
                        breaker_failures=2, breaker_cooldown_s=30.0,
                        deadline_ms=5000.0).start()
    try:
        want = ref.predict(X[:2], raw_score=True)
        oks = 0
        for _ in range(12):
            st, obj, _ = http_json(front.host, front.port, "POST",
                                   "/predict",
                                   {"rows": X[:2].tolist(),
                                    "raw_score": True}, timeout=10)
            assert st == 200, obj
            assert np.array_equal(np.asarray(obj["predictions"]), want)
            oks += 1
        assert oks == 12
        # the dead rank's breaker tripped and now pre-filters it
        assert front.breaker(0).state == "open"
        assert front.breaker(0).trips >= 1
        assert front.breaker(1).state == "closed"
        st, obj, _ = http_json(front.host, front.port, "GET", "/stats",
                               timeout=5)
        assert obj["forwarded"] == 12
        assert obj["breakers"]["0"]["state"] == "open"
    finally:
        front.stop()
        flaky.stop()
        app.shutdown()


def test_front_sheds_when_no_replica_ready(served):
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    front = FanoutFront(
        _StubFleet({0: {"host": "127.0.0.1", "port": dead_port}}),
        port=0, retries=1, retry_backoff_ms=1.0, breaker_failures=1,
        breaker_cooldown_s=30.0, deadline_ms=2000.0).start()
    try:
        st, obj, headers = http_json(front.host, front.port, "POST",
                                     "/predict", {"rows": [[0.0] * 6]},
                                     timeout=10)
        assert st == 503
        assert obj["error"] == "overload"
        assert "Retry-After" in headers
        assert front.shed >= 1
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# promotion pointer mechanics (no processes)
# ---------------------------------------------------------------------------

def test_validate_candidate_rejects_truncation(served, tmp_path):
    pa, _, _, _, _ = served
    text = open(pa).read()
    bad = tmp_path / "trunc.txt"
    bad.write_text(text[:len(text) // 2])
    with pytest.raises(lgb.LightGBMError, match="truncated"):
        validate_candidate(str(bad))
    with pytest.raises(lgb.LightGBMError, match="cannot read"):
        validate_candidate(str(tmp_path / "missing.txt"))


def test_promote_pointer_generations(served, tmp_path):
    pa, pb, _, _, _ = served
    d = str(tmp_path)
    p1 = promote_pointer(d, pa)
    assert p1["generation"] == 1
    p2 = promote_pointer(d, pb)
    assert p2["generation"] == 2
    assert read_pointer(d)["path"] == pb
    # a poisoned candidate never touches the pointer
    bad = tmp_path / "bad.txt"
    bad.write_text(open(pa).read()[:100])
    with pytest.raises(lgb.LightGBMError):
        promote_pointer(d, str(bad))
    assert read_pointer(d)["generation"] == 2


def test_two_concurrent_promoters_cannot_downgrade(served, tmp_path):
    """Two promoters racing on the same fleet dir: the slow one writes a
    pointer with a generation the fleet already moved past.  The replica
    watcher must refuse the backwards pointer (no ``rollback_from``
    marker) instead of silently downgrading the fleet."""
    from lightgbm_tpu.serving.fleet import (pointer_transition,
                                            validate_candidate,
                                            write_pointer)
    pa, pb, _, _, _ = served
    d = str(tmp_path)
    # writer A promotes twice; the fleet's replicas applied generation 2
    promote_pointer(d, pa)
    p2 = promote_pointer(d, pb)
    applied = p2["generation"]
    assert pointer_transition(applied, read_pointer(d)) == "ignore"
    # writer B raced: it read generation 1 before A's second promotion
    # and now writes its (validated, parseable) candidate as generation 2
    # ... then loses the os.replace race and re-writes as the stale gen 1
    sha_a = validate_candidate(pa)
    stale = write_pointer(d, pa, sha_a, 1)
    assert read_pointer(d)["generation"] == 1          # file says 1
    assert pointer_transition(applied, stale) == "refuse"
    assert pointer_transition(applied, read_pointer(d)) == "refuse"
    # only an intentional rollback (the marker rollback_pointer writes)
    # may move a replica's generation backwards
    marked = write_pointer(d, pa, sha_a, 1, rollback_from=applied)
    assert pointer_transition(applied, marked) == "apply"
    # and an unreadable/torn pointer is a no-op, never a downgrade
    assert pointer_transition(applied, None) == "ignore"


def test_rollback_pointer_reverts_to_prev(served, tmp_path):
    """rollback_pointer targets the current pointer's ``prev`` record,
    re-validates it, and stamps ``rollback_from`` so replicas accept the
    downgrade; a fleet with no prior generation refuses to roll back."""
    from lightgbm_tpu.serving.fleet import (generation_history,
                                            rollback_pointer)
    pa, pb, _, _, _ = served
    d = str(tmp_path)
    with pytest.raises(lgb.LightGBMError, match="no prior generation"):
        rollback_pointer(d)
    p1 = promote_pointer(d, pa)
    with pytest.raises(lgb.LightGBMError, match="no prior generation"):
        rollback_pointer(d)                  # generation 1 has no prev
    p2 = promote_pointer(d, pb)
    assert p2["prev"]["generation"] == p1["generation"]
    rb = rollback_pointer(d, reason="slo burn")
    assert rb["generation"] == p1["generation"]
    assert rb["sha256"] == p1["sha256"]
    assert rb["rollback_from"] == p2["generation"]
    assert read_pointer(d)["path"] == str(pa)
    # the audit trail records promote, promote, rollback in order
    gens = [(h["generation"], h.get("rollback_from"))
            for h in generation_history(d)]
    assert gens == [(1, None), (2, None), (1, p2["generation"])]


# ---------------------------------------------------------------------------
# the real fleet: restart-with-backoff + fleet-wide reload (subprocesses)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_restart_reload_and_poisoned_candidate(served, tmp_path):
    pa, pb, X, ref_a, ref_b = served
    oracle = {}
    for path, ref in ((pa, ref_a), (pb, ref_b)):
        sha = validate_candidate(path)
        oracle[sha] = ref.predict(X[:64], raw_score=True)
    fleet = ServingFleet(pa, replicas=2, max_batch=16, buckets_spec="16",
                         max_delay_ms=1.0, deadline_ms=5000.0, retries=2,
                         retry_backoff_ms=5.0, breaker_failures=3,
                         breaker_cooldown_s=0.5, restart_backoff_s=0.2,
                         hang_timeout_s=10.0, binary_port=0).start()
    try:
        def predict(n=3, timeout=10):
            return http_json(fleet.host, fleet.port, "POST", "/predict",
                             {"rows": X[:n].tolist(), "raw_score": True,
                              "deadline_ms": 4000}, timeout=timeout)

        # ---- baseline: exact + sha-stamped
        st, obj, _ = predict()
        assert st == 200, obj
        assert np.array_equal(np.asarray(obj["predictions"]),
                              oracle[obj["model_sha256"]][:3])

        # ---- binary wire: every replica published its own wire port,
        # the front's /stats exposes them for remote discovery, and the
        # replica-aware client scores bitwise through the wire
        from lightgbm_tpu.serving import FleetBinaryClient
        assert sorted(fleet.binary_endpoints()) == [0, 1]
        st, stats, _ = http_json(fleet.host, fleet.port, "GET", "/stats",
                                 timeout=10)
        assert st == 200
        assert sorted(stats["binary_endpoints"]) == ["0", "1"]
        fbc = FleetBinaryClient(fleet.binary_endpoints, attempts=3)
        resp = fbc.request(X[:4], raw_score=True, deadline_ms=4000)
        assert resp["status"] == 0, resp
        assert np.array_equal(np.asarray(resp["predictions"]),
                              oracle[resp["model_sha256"]][:4])

        # ---- kill replica 0: traffic keeps flowing (retry/breaker),
        # the supervisor restarts it with backoff
        os.kill(fleet.endpoint(0)["pid"], signal.SIGKILL)
        t0 = time.time()
        while time.time() - t0 < 3:
            st, obj, _ = predict(n=2, timeout=8)
            assert st in (200, 503), obj      # zero non-503 errors
            if st == 200:
                assert np.array_equal(np.asarray(obj["predictions"]),
                                      oracle[obj["model_sha256"]][:2])
            time.sleep(0.02)
        # the replica-aware binary client routes around the dead wire
        resp = fbc.request(X[:2], raw_score=True, deadline_ms=4000)
        assert resp["status"] == 0, resp
        assert np.array_equal(np.asarray(resp["predictions"]),
                              oracle[resp["model_sha256"]][:2])
        fbc.close()

        def wait_restarted(deadline_s=30):
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                d = fleet.describe()
                r0 = next(r for r in d["replicas"] if r["rank"] == 0)
                if r0["reachable"] and r0.get("ready"):
                    return d
                time.sleep(0.2)
            raise AssertionError(f"replica 0 never came back: {d}")

        d = wait_restarted()
        assert d["restarts_total"] >= 1
        assert next(r for r in d["replicas"]
                    if r["rank"] == 0)["restarts"] >= 1

        # ---- fleet-wide reload through the front: both replicas land
        # on the new generation and serve model B
        st, obj, _ = http_json(fleet.host, fleet.port, "POST", "/reload",
                               {"path": pb}, timeout=60)
        assert st == 200, obj
        assert sorted(obj["promoted"]) == [0, 1]
        assert obj["rejected"] == {}
        gen_b = obj["generation"]
        sha_b = obj["sha256"]
        st, obj, _ = predict()
        assert st == 200 and obj["model_sha256"] == sha_b
        assert np.array_equal(np.asarray(obj["predictions"]),
                              oracle[sha_b][:3])

        # ---- poisoned candidate: passes the pointer (written directly,
        # as an external deploy tool could) but fails every replica's
        # re-validation -> fleet stays on B, degraded state surfaces
        poisoned = tmp_path / "poisoned.txt"
        poisoned.write_text(open(pa).read())
        sha_ok = validate_candidate(str(poisoned))
        from lightgbm_tpu.serving.fleet import write_pointer
        write_pointer(fleet.dir, str(poisoned), sha_ok, gen_b + 1)
        poisoned.write_text(open(pa).read() + "# tampered\n")   # sha drifts
        t0 = time.time()
        while time.time() - t0 < 20:
            d = fleet.describe()
            degraded = [r for r in d["replicas"] if r.get("degraded")]
            if len(degraded) == 2:
                break
            time.sleep(0.2)
        assert len(degraded) == 2, d
        assert all("rejected" in r["degraded"] for r in degraded)
        assert all(r.get("generation") == gen_b for r in d["replicas"])
        st, obj, _ = predict()            # still serving B, bit-exact
        assert st == 200 and obj["model_sha256"] == sha_b
        assert np.array_equal(np.asarray(obj["predictions"]),
                              oracle[sha_b][:3])
        # front /ready surfaces the degraded ranks + breaker states
        # (its readiness cache refreshes every ~0.5 s — poll, don't race)
        t0 = time.time()
        while time.time() - t0 < 10:
            st, obj, _ = http_json(fleet.host, fleet.port, "GET",
                                   "/ready", timeout=5)
            assert st == 200 and obj["ready"]
            if all(r.get("degraded") for r in obj["replicas"]):
                break
            time.sleep(0.2)
        assert all(r.get("degraded") for r in obj["replicas"]), obj
        assert all(r["breaker"] in ("closed", "open", "half_open")
                   for r in obj["replicas"])

        # ---- restart UNDER the poisoned pointer: the rebooted replica
        # must re-validate at boot (not serve the tampered bytes) and
        # wait for a valid promotion instead of crash-looping
        os.kill(fleet.endpoint(1)["pid"], signal.SIGKILL)
        time.sleep(1.0)           # replica 1 is now booting, pointer bad

        # ---- a good promotion clears degraded everywhere, including
        # the replica that rebooted while the pointer was poisoned
        st, obj, _ = http_json(fleet.host, fleet.port, "POST", "/reload",
                               {"path": pa}, timeout=60)
        assert st == 200 and 0 in obj["promoted"], obj
        sha_a = obj["sha256"]
        t0 = time.time()
        while time.time() - t0 < 40:
            d = fleet.describe()
            if (all(r["reachable"] and not r.get("degraded")
                    and r.get("model_sha256") == sha_a
                    for r in d["replicas"])):
                break
            time.sleep(0.3)
        assert all(r["reachable"] and not r.get("degraded")
                   and r.get("model_sha256") == sha_a
                   for r in d["replicas"]), d
        st, obj, _ = predict()
        assert st == 200 and obj["model_sha256"] == sha_a
        assert np.array_equal(np.asarray(obj["predictions"]),
                              oracle[sha_a][:3])
    finally:
        fleet.stop()
    assert not os.path.isdir(fleet.dir)   # owned tmpdir cleaned up


@pytest.mark.skipif(not reuseport_available(),
                    reason="SO_REUSEPORT unavailable on this platform")
def test_reuseport_two_servers_share_port(served):
    pa, _, X, ref, _ = served
    a = ServingApp(pa, port=0, max_batch=8, max_delay_ms=1.0,
                   reuse_port=True).start()
    b = ServingApp(pa, port=a.port, max_batch=8, max_delay_ms=1.0,
                   reuse_port=True).start()
    try:
        assert a.port == b.port
        want = ref.predict(X[:2], raw_score=True)
        for _ in range(6):   # kernel picks a listener per connection
            st, obj, _ = http_json(a.host, a.port, "POST", "/predict",
                                   {"rows": X[:2].tolist(),
                                    "raw_score": True}, timeout=10)
            assert st == 200
            assert np.array_equal(np.asarray(obj["predictions"]), want)
    finally:
        a.shutdown()
        b.shutdown()


def test_fleet_rejects_bad_config(served):
    pa, _, _, _, _ = served
    with pytest.raises(lgb.LightGBMError, match="serve_replicas"):
        ServingFleet(pa, replicas=0)
    with pytest.raises(lgb.LightGBMError, match="serve_fleet_mode"):
        ServingFleet(pa, replicas=1, mode="carrier_pigeon")
