"""Forced splits from a JSON file (reference: serial_tree_learner.cpp:628
ForceSplits, config forcedsplits_filename)."""
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=2000, seed=12):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 5)
    y = X[:, 0] + 2 * X[:, 1] + 0.1 * rs.randn(n)
    return X, y


def test_forced_splits_applied(tmp_path):
    X, y = _data()
    fs = tmp_path / "forced.json"
    fs.write_text(json.dumps({
        "feature": 3, "threshold": 0.0,
        "left": {"feature": 4, "threshold": 0.5},
    }))
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "forcedsplits_filename": str(fs)},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    for t in bst._all_trees():
        # node 0 must split feature 3 at ~0.0; its left child splits feature 4
        assert int(t.split_feature[0]) == 3
        assert abs(float(t.threshold[0])) < 0.2
        lc = int(t.left_child[0])
        assert lc >= 0 and int(t.split_feature[lc]) == 4
        assert abs(float(t.threshold[lc]) - 0.5) < 0.25
    # model still fits despite the forced structure
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.9


def test_forced_splits_too_deep_raises(tmp_path):
    X, y = _data()
    node = {"feature": 0, "threshold": 0.0}
    root = node
    for _ in range(5):
        child = {"feature": 0, "threshold": 0.0}
        node["left"] = child
        node["right"] = {"feature": 1, "threshold": 0.0}
        node = child
    fs = tmp_path / "deep.json"
    fs.write_text(json.dumps(root))
    with pytest.raises(lgb.LightGBMError):
        lgb.train({"objective": "regression", "num_leaves": 4,
                   "verbosity": -1, "forcedsplits_filename": str(fs)},
                  lgb.Dataset(X, label=y), num_boost_round=1)
