"""Fused-sharded iteration tests (docs/DISTRIBUTED.md "fused iteration &
sharded state").

Under a row-sharded stream mesh the default training step is ONE
`watched_jit` launch per boosting iteration (gradients -> sampling ->
growth -> score update) threading a ShardedTrainState whose out-shardings
equal its in-shardings.  This suite proves the fused path against the
unfused one (`LGBTPU_FUSE_ITER=0`) on 4- and 8-way CPU meshes with the
PR 6 identity discipline — the round-1 tree must match BYTE-for-byte
(low-mantissa round-1 gradients make every f32 summation order exact),
later rounds must match structurally with ulp tolerance (XLA re-fuses
the wider program's gradient chain with last-ulp differences) — covering
GOSS compaction, bagging, multiclass-batched lockstep, and
checkpoint/resume from a sharded state.  Runs on the conftest 8-device
CPU mesh and the 4-device tier run_all_tests.sh adds.
"""
import os

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import launch_count

from conftest import make_synthetic_binary, make_synthetic_multiclass

N_DEV = len(jax.devices())
MESHES = [d for d in (4, 8) if d <= N_DEV]
needs_mesh = pytest.mark.skipif(N_DEV < 4, reason="needs a >=4-device mesh")


def _strip_params(model_str: str) -> str:
    return model_str.split("\nparameters:")[0]


def _assert_fused_identity(a: str, b: str):
    """Round-1 byte equality + full structural identity with ulp-tolerant
    float fields (the PR 6 non-associativity discipline)."""
    a, b = _strip_params(a), _strip_params(b)
    ta, tb = a.split("Tree="), b.split("Tree=")
    assert len(ta) == len(tb)
    assert ta[1] == tb[1], "round-1 tree must match byte-for-byte"
    la, lb = a.splitlines(), b.splitlines()
    assert len(la) == len(lb)
    for xa, xb in zip(la, lb):
        if xa == xb:
            continue
        ka, _, va = xa.partition("=")
        kb, _, vb = xb.partition("=")
        assert ka == kb, f"{ka!r} != {kb!r}"
        if ka == "tree_sizes":    # byte lengths of the float reprs
            continue
        fa = np.array([float(t) for t in va.split()])
        fb = np.array([float(t) for t in vb.split()])
        np.testing.assert_allclose(fa, fb, rtol=3e-4, atol=3e-4,
                                   err_msg=ka)


def _train(params, X, y, rounds=4, fuse=None, mesh_dev=None, **ds_kw):
    p = dict(params, verbosity=-1, tree_learner="data",
             hist_backend="stream")
    if mesh_dev:
        p["mesh_shape"] = f"data:{mesh_dev}"
    if fuse is not None:
        os.environ["LGBTPU_FUSE_ITER"] = fuse
    try:
        return lgb.train(p, lgb.Dataset(X, label=y, **ds_kw),
                         num_boost_round=rounds)
    finally:
        if fuse is not None:
            del os.environ["LGBTPU_FUSE_ITER"]


def _fused_vs_unfused(params, X, y, rounds=4, mesh_dev=None, **ds_kw):
    f = _train(params, X, y, rounds, None, mesh_dev, **ds_kw)
    assert f.engine._fused_last, "fused path did not engage by default"
    u = _train(params, X, y, rounds, "0", mesh_dev, **ds_kw)
    assert not u.engine._fused_last
    _assert_fused_identity(f.model_to_string(), u.model_to_string())
    return f


# ---------------------------------------------------------------------------
# fused == unfused identity across mesh widths and comms modes
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize("mesh_dev", MESHES)
@pytest.mark.parametrize("mode", ["psum", "reduce_scatter"])
def test_fused_identity_binary(mesh_dev, mode):
    X, y = make_synthetic_binary(n=2000, f=8)
    _fused_vs_unfused({"objective": "binary", "num_leaves": 15,
                       "min_data_in_leaf": 5, "hist_comms": mode},
                      X, y, mesh_dev=mesh_dev)


@needs_mesh
@pytest.mark.parametrize("mesh_dev", MESHES)
def test_fused_identity_bagging(mesh_dev):
    """Epoch-cached bagging mask rides into the fused program as a jit
    argument — identical draw, identical trees."""
    X, y = make_synthetic_binary(n=2000, f=8)
    _fused_vs_unfused({"objective": "binary", "num_leaves": 15,
                       "min_data_in_leaf": 5,
                       "hist_comms": "reduce_scatter",
                       "bagging_fraction": 0.7, "bagging_freq": 2,
                       "seed": 3}, X, y, rounds=5, mesh_dev=mesh_dev)


@needs_mesh
@pytest.mark.parametrize("mesh_dev", MESHES)
@pytest.mark.slow
def test_fused_identity_goss_compacted(mesh_dev):
    """GOSS draws its mask IN-TRACE from the iteration's gradients (same
    key as the eager path) and compacts rows at the analytic capacity —
    compaction must actually engage, and any covering capacity grows the
    identical tree (out-of-bag pad rows carry exact-zero weights)."""
    X, y = make_synthetic_binary(n=4000, f=8)
    os.environ["LGBTPU_BLOCK_ROWS"] = "256"   # engage compaction at test n
    try:
        f = _fused_vs_unfused(
            {"objective": "binary", "num_leaves": 15,
             "min_data_in_leaf": 5, "hist_comms": "reduce_scatter",
             "data_sample_strategy": "goss", "learning_rate": 0.5,
             "top_rate": 0.1, "other_rate": 0.15},
            X, y, rounds=6, mesh_dev=mesh_dev)
    finally:
        del os.environ["LGBTPU_BLOCK_ROWS"]
    assert f.engine._last_compact_rows > 0, "compaction never engaged"
    assert f.engine._overflow_seen == 0
    assert f.engine._last_sampled_rows > 0


@needs_mesh
@pytest.mark.parametrize("mesh_dev", MESHES)
def test_fused_identity_multiclass_batched(mesh_dev):
    """All K class trees grow in lockstep INSIDE the fused launch
    (grow_tree_k + the stacked score add)."""
    X, y = make_synthetic_multiclass(n=2000, f=8, k=3)
    f = _fused_vs_unfused({"objective": "multiclass", "num_class": 3,
                           "num_leaves": 11, "min_data_in_leaf": 5,
                           "hist_comms": "reduce_scatter"},
                          X, y, rounds=3, mesh_dev=mesh_dev)
    assert f.engine._mc_batched_last


# ---------------------------------------------------------------------------
# sharded-state invariants
# ---------------------------------------------------------------------------

@needs_mesh
def test_state_stays_sharded_across_iterations():
    """Out-sharding == in-sharding: every row-axis state array keeps its
    row sharding across iterations (no implicit re-shard, no host
    round-trip materialization)."""
    X, y = make_synthetic_binary(n=2000, f=8)
    bst = _train({"objective": "binary", "num_leaves": 15,
                  "hist_comms": "reduce_scatter"}, X, y, rounds=4)
    eng = bst.engine
    st = eng._train_state
    assert st is not None and st.score is eng.score
    ax = eng._row_axis
    for name in ("score", "grad", "hess", "leaf_id", "mask"):
        arr = getattr(st, name)
        spec = arr.sharding.spec
        assert ax in tuple(spec), \
            f"state.{name} lost its row sharding: {arr.sharding}"
    # scalar tail stays replicated — one copy per device, no gather needed
    assert tuple(st.finished.sharding.spec) == ()


@needs_mesh
def test_fused_single_launch_per_iteration():
    """The dispatch-count contract: a steady-state fused iteration is ONE
    watched_jit launch (vs >= 3 unfused: gradients + grow + score ops)."""
    X, y = make_synthetic_binary(n=2000, f=8)
    p = {"objective": "binary", "num_leaves": 15,
         "hist_comms": "reduce_scatter"}
    bst = _train(p, X, y, rounds=2)   # warm the caches
    eng = bst.engine
    l0 = launch_count()
    for _ in range(4):
        bst.update()
    launches = (launch_count() - l0) / 4
    assert launches <= 1.5, f"fused path dispatched {launches}/iter"


# ---------------------------------------------------------------------------
# checkpoint / resume from a sharded state
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize("sampling", ["plain", "goss"])
@pytest.mark.slow
def test_checkpoint_resume_from_sharded_state(tmp_path, sampling):
    """A snapshot taken mid-run from the device-sharded state must resume
    BIT-IDENTICALLY — same discipline as the single-chip resume suite,
    now with the score living sharded across the mesh."""
    X, y = make_synthetic_binary(n=2000, f=8)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tree_learner": "data", "hist_backend": "stream",
         "hist_comms": "reduce_scatter", "min_data_in_leaf": 5,
         "snapshot_freq": 3, "snapshot_keep": 8}
    if sampling == "goss":
        p.update({"data_sample_strategy": "goss", "learning_rate": 0.5,
                  "top_rate": 0.2, "other_rate": 0.2})
    out = str(tmp_path / "model.txt")
    full = lgb.train(dict(p, output_model=out), lgb.Dataset(X, label=y),
                     num_boost_round=6)
    assert full.engine._fused_last
    snap = out + ".snapshot_iter_3"
    assert os.path.exists(snap)
    resumed = lgb.train(dict(p, resume_from=snap, output_model=out),
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert _strip_params(full.model_to_string()) == \
        _strip_params(resumed.model_to_string())
