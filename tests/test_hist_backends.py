"""Histogram-formulation floor A/B: backend identity matrix + fusion/packing.

Three candidate formulations ride behind ``hist_backend`` / env
``LGBTPU_HIST_BACKEND`` (docs/PERF.md "histogram-formulation floor"):

  * ``scatter`` — Pallas scatter-add into a VMEM tile (no one-hot operand).
    Bitwise-identical to ``segsum`` at the op level AND as trained models
    once ``hist_precision=single`` is pinned (segsum/onehot auto-resolve
    double on CPU; scatter is single-only).  VMEM-gated with an automatic
    one-hot fallback.
  * ``hist_packed_width`` 16/8 — the quantized grad/hess pair rides one
    int32/int16 wire lane through the mesh collective, halving/quartering
    psum_scatter bytes.  Kernel arithmetic stays exact int32; only the
    collective seam packs.  w16 is drift-free at test scale; w8 is the
    documented-ulp opt-in.
  * ``route_fusion`` — GOSS+stream fusion: per-round full-data route-only
    passes are replaced by ONE post-growth replay launch
    (pallas/stream_kernel.route_replay), bit-identical by construction
    (the replay kernel shares _route_step with the fused route+hist
    kernel).  hist/route_only_passes telemetry is the A/B signal.

GOSS warmup gotcha baked into every sampled test here: sampling starts
after ceil(1/learning_rate) iterations (sample_strategy._is_warmup), so
fusion/compaction only engages with learning_rate=0.5 and >=4 rounds.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
import lightgbm_tpu.telemetry as tel
from lightgbm_tpu.ops.histogram import build_histograms
from lightgbm_tpu.pallas.scatter_hist_kernel import scatter_hist_fits
from lightgbm_tpu.utils.log import LightGBMError

from conftest import (make_synthetic_binary, make_synthetic_multiclass,
                      make_synthetic_regression)

N_DEV = len(jax.devices())
needs_mesh = pytest.mark.skipif(N_DEV < 4, reason="needs a >=4-device mesh")


def _strip_params(model_str: str) -> str:
    """Model text minus the parameters block (backend knobs differ by
    design; every tree byte must still match)."""
    return model_str.split("\nparameters:")[0]


def _datasets():
    """Identity-matrix layouts: numeric+NaN, categorical, EFB-bundled."""
    rs = np.random.RandomState(7)
    out = []

    X, y = make_synthetic_binary(n=1500, f=8)
    X = X.copy()
    X[::13, 2] = np.nan                       # MissingType::NaN routing
    out.append(("binary_nan", {"objective": "binary"},
                dict(data=X, label=y), {}))

    Xr, yr = make_synthetic_regression(n=1200, f=8, seed=7)
    Xr = Xr.copy()
    Xr[:, 3] = rs.randint(0, 6, len(Xr))      # categorical column
    out.append(("reg_cat", {"objective": "regression"},
                dict(data=Xr, label=yr), {"categorical_feature": [3]}))

    # sparse one-hot-ish block -> EFB bundles several features per group
    Xs = np.zeros((1000, 12))
    Xs[:, :4] = rs.randn(1000, 4)
    hot = rs.randint(4, 12, 1000)
    Xs[np.arange(1000), hot] = 1.0
    ys = Xs[:, 0] + 2.0 * (hot == 5) - (hot == 9) + 0.05 * rs.randn(1000)
    out.append(("reg_efb", {"objective": "regression"},
                dict(data=Xs, label=ys), {}))
    return out


def _train(params, data_kw, ds_kw, backend, rounds=6, **extra):
    # max_bin=63 keeps Bmax under the scatter VMEM gate (128) so the
    # scatter kernel actually runs instead of its one-hot fallback
    p = dict(params, num_leaves=15, verbosity=-1, min_data_in_leaf=5,
             max_bin=63, hist_backend=backend, hist_precision="single",
             **extra)
    ds = lgb.Dataset(data_kw["data"], label=data_kw["label"],
                     weight=data_kw.get("weight"), **ds_kw)
    return lgb.train(p, ds, num_boost_round=rounds)


# ---------------------------------------------------------------------------
# op-level identity + VMEM gate
# ---------------------------------------------------------------------------

def _op_inputs(n=4096, g=4, bmax=32, s=8, seed=0):
    rs = np.random.RandomState(seed)
    bins = jnp.asarray(rs.randint(0, bmax, size=(n, g)), jnp.uint8)
    slot = jnp.asarray(rs.randint(-1, s, size=(n,)), jnp.int32)
    grad = jnp.asarray(rs.randn(n), jnp.float32)
    hess = jnp.asarray(rs.rand(n) + 0.1, jnp.float32)
    cnt = jnp.asarray((rs.rand(n) > 0.1), jnp.float32)
    return bins, slot, grad, hess, cnt, s, bmax


def test_scatter_op_bitwise_vs_segsum():
    bins, slot, grad, hess, cnt, s, bmax = _op_inputs()
    assert scatter_hist_fits(s, bins.shape[1], bmax)
    h_sc = build_histograms(bins, slot, grad, hess, cnt, s, bmax,
                            backend="scatter")
    h_ss = build_histograms(bins, slot, grad, hess, cnt, s, bmax,
                            backend="segsum")
    # same row-major accumulation order as segment_sum -> byte equality
    assert np.array_equal(np.asarray(h_sc), np.asarray(h_ss))
    # one-hot reassociates the sum: allclose, not byte-equal
    h_oh = build_histograms(bins, slot, grad, hess, cnt, s, bmax,
                            backend="onehot")
    np.testing.assert_allclose(np.asarray(h_sc), np.asarray(h_oh),
                               rtol=1e-5, atol=1e-5)


def test_scatter_vmem_gate_falls_back_to_onehot():
    # bmax > 128 refuses the scatter tile -> automatic one-hot fallback
    bins, slot, grad, hess, cnt, s, _ = _op_inputs(bmax=32)
    bmax = 200
    assert not scatter_hist_fits(s, bins.shape[1], bmax)
    h_sc = build_histograms(bins, slot, grad, hess, cnt, s, bmax,
                            backend="scatter")
    h_oh = build_histograms(bins, slot, grad, hess, cnt, s, bmax,
                            backend="onehot")
    assert np.array_equal(np.asarray(h_sc), np.asarray(h_oh))
    # and the fallback is still a correct histogram
    h_ss = build_histograms(bins, slot, grad, hess, cnt, s, bmax,
                            backend="segsum")
    np.testing.assert_allclose(np.asarray(h_sc), np.asarray(h_ss),
                               rtol=1e-5, atol=1e-5)
    # group-count gate (G > 64) closes too
    assert not scatter_hist_fits(s, 65, 32)


# ---------------------------------------------------------------------------
# trained-model identity matrix (CPU fast tier)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,params,data_kw,ds_kw", _datasets())
def test_scatter_model_bitwise_vs_segsum(name, params, data_kw, ds_kw):
    """scatter grows the SAME trees as segsum byte-for-byte once
    hist_precision=single is pinned (the default auto resolves double for
    segsum on CPU but scatter is single-only — that A/B would compare
    precisions, not formulations)."""
    a = _train(params, data_kw, ds_kw, "segsum")
    b = _train(params, data_kw, ds_kw, "scatter")
    # the scatter tile must actually fit (else this compares the one-hot
    # fallback, not the formulation under test)
    dd = b.engine.dd
    assert scatter_hist_fits(14, dd.num_groups, dd.max_bins)
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())


@pytest.mark.slow
def test_scatter_multiclass_and_bagging_identity():
    X, y = make_synthetic_multiclass(n=1200, f=8, k=3)
    mc = {"objective": "multiclass", "num_class": 3}
    a = _train(mc, dict(data=X, label=y), {}, "segsum")
    b = _train(mc, dict(data=X, label=y), {}, "scatter")
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())

    Xb, yb = make_synthetic_binary(n=1500, f=8)
    bag = {"objective": "binary", "bagging_fraction": 0.6,
           "bagging_freq": 1, "bagging_seed": 3}
    a = _train(bag, dict(data=Xb, label=yb), {}, "segsum")
    b = _train(bag, dict(data=Xb, label=yb), {}, "scatter")
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())


def test_scatter_goss_identity():
    X, y = make_synthetic_binary(n=2000, f=8)
    goss = {"objective": "binary", "data_sample_strategy": "goss",
            "top_rate": 0.2, "other_rate": 0.2, "learning_rate": 0.5}
    a = _train(goss, dict(data=X, label=y), {}, "segsum", rounds=6)
    b = _train(goss, dict(data=X, label=y), {}, "scatter", rounds=6)
    assert b.engine._last_compact_rows > 0   # sampling actually engaged
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())


@pytest.mark.slow
def test_checkpoint_resume_identity_per_backend(tmp_path):
    """Straight-through vs save_model+init_model continuation must agree
    under every CPU backend (text round-trip requantizes leaf values, so
    allclose rather than byte equality — test_continued.py's contract)."""
    X, y = make_synthetic_binary(n=1200, f=8)
    Xv = X[:200]
    for backend in ("segsum", "onehot", "scatter", "stream"):
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "min_data_in_leaf": 5, "max_bin": 63,
                  "hist_backend": backend, "hist_precision": "single"}
        ds = lgb.Dataset(X, label=y)
        full = lgb.train(params, ds, num_boost_round=8)
        half = lgb.train(params, ds, num_boost_round=4)
        path = str(tmp_path / f"ckpt_{backend}.txt")
        half.save_model(path)
        resumed = lgb.train(params, lgb.Dataset(X, label=y),
                            num_boost_round=4, init_model=path)
        np.testing.assert_allclose(resumed.predict(Xv), full.predict(Xv),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"backend={backend}")


# ---------------------------------------------------------------------------
# engine-first validation + env overrides
# ---------------------------------------------------------------------------

def _tiny():
    X, y = make_synthetic_binary(n=400, f=4)
    return lgb.Dataset(X, label=y)


def _expect_error(params, match):
    with pytest.raises(LightGBMError, match=match):
        lgb.train(dict(params, verbosity=-1, num_leaves=7), _tiny(),
                  num_boost_round=1)


def test_invalid_backend_rejected_before_training():
    _expect_error({"objective": "binary", "hist_backend": "vector"},
                  "hist_backend")


def test_scatter_rejects_feature_parallel():
    _expect_error({"objective": "binary", "hist_backend": "scatter",
                   "tree_learner": "feature"}, "single-device")


def test_scatter_rejects_double_precision():
    _expect_error({"objective": "binary", "hist_backend": "scatter",
                   "hist_precision": "double"}, "double")


def test_packed_width_validation():
    _expect_error({"objective": "binary", "hist_packed_width": 12},
                  "hist_packed_width")
    _expect_error({"objective": "binary", "hist_packed_width": 16},
                  "use_quantized_grad")
    _expect_error({"objective": "regression", "hist_packed_width": 16,
                   "use_quantized_grad": True, "linear_tree": True},
                  "linear")


def test_route_fusion_validation():
    _expect_error({"objective": "binary", "route_fusion": "maybe"},
                  "route_fusion")


def test_env_override_hist_backend(monkeypatch):
    monkeypatch.setenv("LGBTPU_HIST_BACKEND", "scatter")
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, _tiny(), num_boost_round=1)
    assert bst.engine._grow_params.hist_backend == "scatter"
    monkeypatch.setenv("LGBTPU_HIST_BACKEND", "vector")
    with pytest.raises(LightGBMError, match="hist_backend"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "num_leaves": 7}, _tiny(), num_boost_round=1)


def test_env_override_packed_width(monkeypatch):
    monkeypatch.setenv("LGBTPU_HIST_PACKED_WIDTH", "16")
    X, y = make_synthetic_binary(n=400, f=4)
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                     "use_quantized_grad": True},
                    lgb.Dataset(X, label=y), num_boost_round=1)
    assert bst.engine._grow_params.hist_packed_width == 16


# ---------------------------------------------------------------------------
# GOSS+stream fusion (single device)
# ---------------------------------------------------------------------------

_FUSION_PARAMS = {
    "objective": "binary", "num_leaves": 127, "verbosity": -1,
    "min_data_in_leaf": 5, "hist_backend": "stream",
    "data_sample_strategy": "goss", "top_rate": 0.1, "other_rate": 0.1,
    "learning_rate": 0.5, "max_splits_per_round": 64,
}


def _train_fusion(X, y, fusion, rounds=6, **extra):
    p = dict(_FUSION_PARAMS, route_fusion=fusion, **extra)
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


def test_route_fusion_bitwise_identity():
    """Fusion on vs off grows byte-identical models: the replay kernel
    shares _route_step with the fused route+hist kernel, and unused
    zero-table buffer rows are exact no-op steps."""
    X, y = make_synthetic_binary(n=4096, f=10)
    a = _train_fusion(X, y, "off")
    b = _train_fusion(X, y, "on")
    assert a.engine._last_compact_rows > 0   # GOSS past warmup
    assert b.engine._route_only_passes_per_tree() == 1       # fused
    assert a.engine._route_only_passes_per_tree() > 1        # per-round
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())


@pytest.mark.slow
def test_route_fusion_gate_respects_categoricals():
    # categorical trees carry bitset overlays the round tables don't
    # encode -> the fusion gate must fall back to per-round routing
    rs = np.random.RandomState(3)
    X, y = make_synthetic_binary(n=4096, f=10)
    X = X.copy()
    X[:, 1] = rs.randint(0, 12, len(X))
    p = dict(_FUSION_PARAMS, route_fusion="on")
    bst = lgb.train(p, lgb.Dataset(X, label=y, categorical_feature=[1]),
                    num_boost_round=6)
    assert bst.engine._grow_params.has_categorical
    assert bst.engine._route_only_passes_per_tree() > 1


def test_route_only_passes_telemetry():
    tel.reset()
    tel.configure(enabled=True)
    try:
        X, y = make_synthetic_binary(n=4096, f=10)
        bst = _train_fusion(X, y, "off", telemetry=True)
        snap = tel.global_registry.snapshot()
        assert snap["counters"]["hist/route_only_passes"] > 0
        iters = [r for r in tel.global_registry.records
                 if r.get("event") == "iteration"]
        assert iters and all(r["hist_backend"] == "stream" for r in iters)
        # post-warmup iterations route per round; fused run drops to 1/tree
        per_tree = bst.engine._route_only_passes_per_tree()
        assert per_tree > 1
        assert any(r["route_only_passes"] == per_tree for r in iters)
    finally:
        tel.disable()
        tel.reset()
        tel.configure(enabled=False, metrics_out="", trace_out="")


# ---------------------------------------------------------------------------
# mesh tier: packed wire widths + fused replay under shard_map
# ---------------------------------------------------------------------------

def _train_mesh(params, X, y, rounds=6):
    p = dict(params, verbosity=-1, min_data_in_leaf=5,
             tree_learner="data", hist_backend="stream")
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


_PACK_BASE = {"objective": "binary", "num_leaves": 31,
              "use_quantized_grad": True, "num_grad_quant_bins": 16}


@needs_mesh
@pytest.mark.slow
@pytest.mark.parametrize("comms", ["psum", "reduce_scatter"])
def test_packed16_mesh_identity_and_bytes(comms):
    """int16 packed wire halves the per-round collective payload and (at
    this scale/quant config) stays byte-identical to the exact int32 wire
    under BOTH hist_comms modes; int8 quarters the bytes (documented-ulp
    — structural sanity only)."""
    X, y = make_synthetic_binary(n=4096, f=10)
    models, bytes_ = {}, {}
    for w in (32, 16, 8):
        p = dict(_PACK_BASE, hist_comms=comms, hist_packed_width=w)
        bst = _train_mesh(p, X, y)
        cm = bst.engine._comms_model()
        assert cm["packed_width"] == w
        models[w], bytes_[w] = bst, cm["per_round_bytes"]
    # only the histogram payload packs; reduce_scatter also all_gathers
    # fixed-size best-split records (d * S * 7 fields * 4 bytes) that
    # ride outside the packed wire
    gp = models[32].engine._grow_params
    S = min(gp.max_splits_per_round, gp.num_leaves - 1)
    cm32 = models[32].engine._comms_model()
    rec = 0 if comms == "psum" else cm32["devices"] * S * 7 * 4
    assert (bytes_[16] - rec) * 2 == bytes_[32] - rec
    assert (bytes_[8] - rec) * 4 == bytes_[32] - rec
    assert _strip_params(models[16].model_to_string()) == \
        _strip_params(models[32].model_to_string())
    # w8 saturates the 8-bit lane at this quant config: different trees by
    # design, but still a usable model
    pred8 = models[8].predict(X[:256])
    assert np.all(np.isfinite(pred8))


def test_packed_width_single_device_noop():
    # no mesh -> no collective seam: packed widths must be a strict no-op
    X, y = make_synthetic_binary(n=1500, f=8)
    p = dict(_PACK_BASE, verbosity=-1, min_data_in_leaf=5)
    a = lgb.train(dict(p, hist_packed_width=32), lgb.Dataset(X, label=y),
                  num_boost_round=5)
    b = lgb.train(dict(p, hist_packed_width=16), lgb.Dataset(X, label=y),
                  num_boost_round=5)
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())


@needs_mesh
@pytest.mark.slow
def test_route_fusion_mesh_identity():
    # per-shard compaction needs enough local rows to beat the block
    # quantum: 32768 rows -> 4096/shard on the 8-device CPU mesh
    X, y = make_synthetic_binary(n=32768, f=10)
    p_off = dict(_FUSION_PARAMS, route_fusion="off", tree_learner="data")
    p_on = dict(_FUSION_PARAMS, route_fusion="on", tree_learner="data")
    a = lgb.train(p_off, lgb.Dataset(X, label=y), num_boost_round=5)
    b = lgb.train(p_on, lgb.Dataset(X, label=y), num_boost_round=5)
    assert b.engine._last_compact_rows > 0
    assert _strip_params(a.model_to_string()) == \
        _strip_params(b.model_to_string())


# ---------------------------------------------------------------------------
# unit tier: wire-packing algebra, the comms byte model, and the scatter
# VMEM gate — pure math, no training, so they stay in the fast tier even
# on a throttled box
# ---------------------------------------------------------------------------

from lightgbm_tpu.parallel.comms import (hist_comms_bytes_per_round,
                                         pack_gh_wire, unpack_gh_wire)


def _gh_block(rng, g_lo, g_hi, h_hi, shape=(4, 6, 8)):
    g = rng.integers(g_lo, g_hi, size=shape).astype(np.int32)
    h = rng.integers(0, h_hi, size=shape).astype(np.int32)
    return jnp.stack([jnp.asarray(g), jnp.asarray(h)], axis=-1)


def test_pack_roundtrip_exact_w16():
    # magnitudes under cap -> shift 0 -> bit-exact roundtrip
    h = _gh_block(np.random.default_rng(0), -2000, 2000, 1000)
    packed, scales = pack_gh_wire(h, None, 16, d=4)
    out = unpack_gh_wire(packed, scales, 16)
    assert np.array_equal(np.asarray(scales), [1.0, 1.0])
    assert np.array_equal(np.asarray(out), np.asarray(h, dtype=np.float32))


def test_pack_roundtrip_exact_w8():
    h = _gh_block(np.random.default_rng(1), -20, 20, 25)
    packed, scales = pack_gh_wire(h, None, 8, d=4)
    out = unpack_gh_wire(packed, scales, 8)
    assert np.array_equal(np.asarray(scales), [1.0, 1.0])
    assert np.array_equal(np.asarray(out), np.asarray(h, dtype=np.float32))


def test_pack_wire_dtypes():
    h = _gh_block(np.random.default_rng(2), -5, 5, 5)
    assert pack_gh_wire(h, None, 16, d=4)[0].dtype == jnp.int32
    assert pack_gh_wire(h, None, 8, d=4)[0].dtype == jnp.int16


@pytest.mark.parametrize("width", [16, 8])
def test_pack_requantized_error_bounded_by_half_scale(width):
    # magnitudes over cap -> pow2 shift with round-half-away: each field's
    # error is at most scale/2 (the documented-ulp contract)
    rng = np.random.default_rng(3)
    h = _gh_block(rng, -10 ** 6, 10 ** 6, 10 ** 6)
    packed, scales = pack_gh_wire(h, None, width, d=4)
    s = np.asarray(scales)
    assert s[0] > 1.0 and s[1] > 1.0  # really requantized
    assert float(np.log2(s[0])) % 1 == 0.0  # pow2 shift
    out = np.asarray(unpack_gh_wire(packed, scales, width))
    ref = np.asarray(h, dtype=np.float32)
    assert np.max(np.abs(out[..., 0] - ref[..., 0])) <= s[0] / 2
    assert np.max(np.abs(out[..., 1] - ref[..., 1])) <= s[1] / 2


@pytest.mark.parametrize("width", [16, 8])
def test_pack_sum_linearity_carry_free(width):
    # the collective sums PACKED lanes: with shift 0 on every shard the
    # unpacked sum must equal the sum of the unpacked shards exactly —
    # the hess field never carries into the grad field above it
    rng = np.random.default_rng(4)
    d = 4
    lim = (2000, 1000) if width == 16 else (20, 25)
    blocks = [_gh_block(rng, -lim[0], lim[0], lim[1]) for _ in range(d)]
    packed = []
    for b in blocks:
        p, scales = pack_gh_wire(b, None, width, d=d)
        assert np.array_equal(np.asarray(scales), [1.0, 1.0])
        packed.append(np.asarray(p, dtype=np.int32))
    summed = jnp.asarray(sum(packed))
    out = np.asarray(unpack_gh_wire(summed, scales, width))
    ref = np.asarray(sum(np.asarray(b, dtype=np.int64) for b in blocks),
                     dtype=np.float32)
    assert np.array_equal(out, ref)


def test_bytes_model_psum_halves_and_quarters():
    kw = dict(num_slots=64, num_groups=28, bmax=63, d=4, mode="psum")
    b32 = hist_comms_bytes_per_round(**kw, packed_width=32)
    assert b32 == 64 * 28 * 63 * 2 * 4
    assert hist_comms_bytes_per_round(**kw, packed_width=16) * 2 == b32
    assert hist_comms_bytes_per_round(**kw, packed_width=8) * 4 == b32


def test_bytes_model_psum_d_invariant_and_class_scaling():
    kw = dict(num_slots=32, num_groups=8, bmax=32, mode="psum")
    assert hist_comms_bytes_per_round(**kw, d=2) == \
        hist_comms_bytes_per_round(**kw, d=8)
    assert hist_comms_bytes_per_round(**kw, d=4, num_class=3) == \
        3 * hist_comms_bytes_per_round(**kw, d=4)


def test_bytes_model_reduce_scatter_packs_block_not_records():
    kw = dict(num_slots=64, num_groups=32, bmax=63, d=4,
              mode="reduce_scatter")
    rec = 4 * 64 * 7 * 4  # d shards x 7-field f32 best records
    b32 = hist_comms_bytes_per_round(**kw, packed_width=32)
    b16 = hist_comms_bytes_per_round(**kw, packed_width=16)
    b8 = hist_comms_bytes_per_round(**kw, packed_width=8)
    assert (b16 - rec) * 2 == b32 - rec
    assert (b8 - rec) * 4 == b32 - rec
    # bf16_pair also halves the slice, and only the slice
    bf = hist_comms_bytes_per_round(**kw, dtype="bf16_pair")
    assert (bf - rec) * 2 == b32 - rec


def test_scatter_fits_bin_and_group_caps():
    assert scatter_hist_fits(14, 4, 128)
    assert not scatter_hist_fits(14, 4, 129)   # > one 128-lane tile
    assert scatter_hist_fits(14, 64, 32)
    assert not scatter_hist_fits(14, 65, 32)   # static unroll cap


def test_scatter_fits_vmem_budget_boundary():
    # tile = S * G * B * cp * 4 with cp=4 (binary): S*64*128*16 bytes
    # crosses the 12 MB budget exactly between S=96 and S=97
    assert scatter_hist_fits(96, 64, 128)
    assert not scatter_hist_fits(97, 64, 128)


def test_scatter_fits_multiclass_widens_channels():
    # num_class=3 -> 9 channels pad to 12: budget shrinks 3x vs binary
    # (S=32 x 3 classes lands EXACTLY on the 12 MB budget and still fits)
    assert scatter_hist_fits(32, 64, 128, num_class=3)
    assert scatter_hist_fits(33, 64, 128)
    assert not scatter_hist_fits(33, 64, 128, num_class=3)


def test_unpack_floored_mod_keeps_low_field():
    # the low (hess) field is non-negative by construction; floored
    # mod/div must recover it even under a negative packed lane
    packed = jnp.asarray([[-3 * 65536 + 7, 5 * 65536 + 9]], dtype=jnp.int32)
    out = np.asarray(unpack_gh_wire(packed, jnp.asarray([1.0, 1.0]), 16))
    assert np.array_equal(out[..., 0], [[-3.0, 5.0]])
    assert np.array_equal(out[..., 1], [[7.0, 9.0]])


def test_pack_shift_is_exact_pow2_of_overflow():
    # one element at 4x the field cap -> shift exactly 2 -> scale 4.0
    d = 1
    cap = (2 ** 15 - 8) // d
    h = jnp.asarray([[4 * cap, 0]], dtype=jnp.int32)[None]
    _, scales = pack_gh_wire(h, None, 16, d=d)
    assert float(scales[0]) == 4.0


def test_bytes_model_rs_pads_groups_to_d():
    # G=30 over d=4 -> 8-group slices, same as G=32
    kw = dict(num_slots=16, bmax=32, d=4, mode="reduce_scatter")
    assert hist_comms_bytes_per_round(num_groups=30, **kw) == \
        hist_comms_bytes_per_round(num_groups=32, **kw)


def test_bytes_model_packed_width_overrides_bf16_pair():
    # a packed wire IS the narrow dtype: bf16_pair cannot narrow it again
    kw = dict(num_slots=16, num_groups=8, bmax=32, d=4,
              mode="reduce_scatter", packed_width=16)
    assert hist_comms_bytes_per_round(dtype="bf16_pair", **kw) == \
        hist_comms_bytes_per_round(dtype="f32", **kw)


def test_scatter_block_rows_shrinks_with_classes():
    from lightgbm_tpu.pallas.scatter_hist_kernel import scatter_block_rows
    assert scatter_block_rows(28) == 8192
    assert scatter_block_rows(28, num_class=4) == 2048
    # floor: never below one 1024-row grid step
    assert scatter_block_rows(28, num_class=64) == 1024
