"""Out-of-core streaming ingest (docs/INGEST.md).

Covers the PR's gate surface: sketch-vs-exact boundary equivalence
(incl. NaN / zero / min_data_in_bin / zero_as_missing / categorical
edge cases), chunk-boundary and rank-split determinism, stream-vs-inmem
tree BIT-identity, the memory-mapped binned cache (hit, corruption
matrix, auto fallback), checkpoint/resume from a streamed ingest, the
chunked device ship, and the eager-memory fixes.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import BinMapper, construct_binned
from lightgbm_tpu.ingest import (BottomKSample, FeatureSketch,
                                 _merge_rank_blobs, _pack_rank_blob,
                                 resolve_ingest_mode)
from lightgbm_tpu.utils.log import LightGBMError

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5, "bin_construct_sample_cnt": 50000,
          "ingest_sketch_size": 65536}


def _write_csv(path, X, y, fmt="%.17g"):
    with open(path, "w") as f:
        for i in range(len(X)):
            f.write(f"{y[i]:.0f}," + ",".join(
                "" if np.isnan(v) else fmt % v for v in X[i]) + "\n")
    return str(path)


def _make_data(n=4000, F=5, seed=3, nan_frac=0.03):
    rng = np.random.RandomState(seed)
    X = np.round(rng.randn(n, F), 2)
    if nan_frac:
        X[rng.rand(n, F) < nan_frac] = np.nan
    y = (X[:, 0] + 0.5 * np.nan_to_num(X[:, 1])
         + rng.randn(n) * 0.3 > 0).astype(float)
    return X, y


def _train_env(csv, mode, chunk=None, extra=None, rounds=8):
    """Train with the ingest A/B env overrides so the recorded params —
    and therefore the model string — are byte-comparable across arms."""
    os.environ["LGBTPU_INGEST"] = mode
    if chunk:
        os.environ["LGBTPU_INGEST_CHUNK"] = str(chunk)
    try:
        p = {**PARAMS, **(extra or {})}
        ds = lgb.Dataset(csv, params=p)
        return lgb.train(p, ds, num_boost_round=rounds), ds
    finally:
        os.environ.pop("LGBTPU_INGEST", None)
        os.environ.pop("LGBTPU_INGEST_CHUNK", None)


# ---------------------------------------------------------------------------
# Sketch-vs-exact boundary equivalence
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("use_missing,zero_as_missing", [
    (True, False), (True, True), (False, False)])
@pytest.mark.parametrize("min_data_in_bin", [1, 3, 50])
def test_sketch_matches_find_numerical(use_missing, zero_as_missing,
                                       min_data_in_bin):
    rng = np.random.RandomState(0)
    col = rng.choice(np.round(rng.randn(300), 2), 20000)
    col[rng.rand(20000) < 0.05] = np.nan
    col[rng.rand(20000) < 0.2] = 0.0
    ref = BinMapper.find_numerical(col, 63, min_data_in_bin, use_missing,
                                   zero_as_missing)
    for chunk in (137, 4096, len(col)):
        sk = FeatureSketch(65536)
        for s in range(0, len(col), chunk):
            sk.update(col[s:s + chunk])
        assert sk.exact
        m = sk.find_mapper(63, min_data_in_bin, use_missing,
                           zero_as_missing)
        np.testing.assert_array_equal(m.upper_bounds, ref.upper_bounds)
        assert (m.num_bins, m.missing_type, m.default_bin,
                m.most_freq_bin, m.min_val, m.max_val) == \
               (ref.num_bins, ref.missing_type, ref.default_bin,
                ref.most_freq_bin, ref.min_val, ref.max_val)


def test_sketch_merge_equals_whole_and_is_order_invariant():
    rng = np.random.RandomState(1)
    col = rng.choice(np.round(rng.randn(400), 3), 9000)
    col[rng.rand(9000) < 0.1] = np.nan
    whole = FeatureSketch(65536)
    whole.update(col)
    for cut in (1, 1234, 8999):
        a, b = FeatureSketch(65536), FeatureSketch(65536)
        a.update(col[:cut])
        b.update(col[cut:])
        b.merge(a)  # reversed merge order too
        np.testing.assert_array_equal(b.values, whole.values)
        np.testing.assert_array_equal(b.counts, whole.counts)
        assert (b.na_cnt, b.total) == (whole.na_cnt, whole.total)


def test_sketch_categorical_matches_find_categorical():
    rng = np.random.RandomState(2)
    col = rng.choice([0, 1, 2, 5, 5.7, 100, -3, np.nan], 8000,
                     p=[.3, .2, .15, .1, .05, .05, .05, .1])
    ref = BinMapper.find_categorical(col, 10, 3, True)
    sk = FeatureSketch(65536, is_cat=True)
    for s in range(0, len(col), 997):
        sk.update(col[s:s + 997])
    m = sk.find_mapper(10, 3, True, False)
    np.testing.assert_array_equal(m.categories, ref.categories)
    assert (m.num_bins, m.missing_type) == (ref.num_bins, ref.missing_type)


def test_sketch_trivial_and_all_nan_columns():
    for col in (np.full(100, 7.0), np.full(100, np.nan),
                np.zeros(100)):
        ref = BinMapper.find_numerical(col, 255, 3, True, False)
        sk = FeatureSketch(1024)
        sk.update(col[:37])
        sk.update(col[37:])
        m = sk.find_mapper(255, 3, True, False)
        np.testing.assert_array_equal(m.upper_bounds, ref.upper_bounds)
        assert (m.num_bins, m.missing_type) == (ref.num_bins,
                                                ref.missing_type)


def test_compressed_sketch_tracks_quantiles():
    rng = np.random.RandomState(5)
    big = rng.randn(200000)
    sk = FeatureSketch(1024)
    for s in range(0, len(big), 4096):
        sk.update(big[s:s + 4096])
    assert not sk.exact
    m = sk.find_mapper(255, 3, True, False)
    assert m.num_bins <= 256
    assert np.all(np.diff(m.upper_bounds[:-1]) > 0)
    # every bin holds roughly uniform mass: boundary rank error small
    q = np.searchsorted(np.sort(big), m.upper_bounds[:-1]) / len(big)
    assert np.abs(np.diff(q) - 1.0 / m.num_bins).max() < 0.02
    # min/max survive compression exactly
    assert m.min_val == big.min() and m.max_val == big.max()


# ---------------------------------------------------------------------------
# Bottom-k pool + rank merge determinism
# ---------------------------------------------------------------------------

def test_bottom_k_pool_chunk_and_rank_invariant():
    rng = np.random.RandomState(7)
    X = rng.randn(5000, 4)
    ref = BottomKSample(600, seed=1)
    ref.offer(0, X)
    want = ref.finalize()
    # chunked offers
    p2 = BottomKSample(600, seed=1)
    for s in range(0, 5000, 333):
        p2.offer(s, X[s:s + 333])
    np.testing.assert_array_equal(p2.finalize(), want)
    # rank-split merge
    a, b = BottomKSample(600, seed=1), BottomKSample(600, seed=1)
    a.offer(0, X[:2100])
    b.offer(2100, X[2100:])
    merged = BottomKSample.merged([a.state(), b.state()], 600, seed=1)
    np.testing.assert_array_equal(merged.finalize(), want)


def test_bottom_k_pool_small_n_is_all_rows_in_order():
    X = np.arange(50, dtype=float).reshape(25, 2)
    p = BottomKSample(100, seed=9)
    p.offer(0, X[:11])
    p.offer(11, X[11:])
    np.testing.assert_array_equal(p.finalize(), X)


def test_rank_blob_pack_merge_roundtrip():
    """The ONE-collective payload: splitting rows across simulated ranks
    and merging the gathered blobs reproduces the single-rank state."""
    rng = np.random.RandomState(11)
    col = np.round(rng.randn(4000), 2)
    X = np.column_stack([col, rng.choice([1, 2, 3], 4000).astype(float)])
    F, budget, k = 2, 4096, 500
    whole_sk = [FeatureSketch(budget), FeatureSketch(budget, is_cat=True)]
    for f in range(F):
        whole_sk[f].update(X[:, f])
    whole_pool = BottomKSample(k, seed=1)
    whole_pool.offer(0, X)

    wire_w = FeatureSketch.wire_width(budget)
    blobs = []
    for (lo, hi) in ((0, 1500), (1500, 4000)):
        sks = [FeatureSketch(budget), FeatureSketch(budget, is_cat=True)]
        for f in range(F):
            sks[f].update(X[lo:hi, f])
        pool = BottomKSample(k, seed=1)
        pool.offer(lo, X[lo:hi])
        blobs.append(_pack_rank_blob(sks, pool, wire_w, k, F))
    gathered = np.stack(blobs)
    sks, pool = _merge_rank_blobs(gathered, budget, wire_w, k, F, seed=1,
                                  want_pool=True)
    for f in range(F):
        np.testing.assert_array_equal(sks[f].values, whole_sk[f].values)
        np.testing.assert_array_equal(sks[f].counts, whole_sk[f].counts)
        assert sks[f].na_cnt == whole_sk[f].na_cnt
        assert sks[f].total == whole_sk[f].total
    np.testing.assert_array_equal(pool.finalize(), whole_pool.finalize())


# ---------------------------------------------------------------------------
# End-to-end: stream vs inmem, chunk determinism, sources
# ---------------------------------------------------------------------------

def test_stream_vs_inmem_trees_bit_identical(tmp_path):
    X, y = _make_data()
    csv = _write_csv(tmp_path / "t.csv", X, y)
    b_in, _ = _train_env(csv, "inmem")
    b_st, ds = _train_env(csv, "stream", 700)
    assert b_in.model_to_string() == b_st.model_to_string()
    assert ds.ingest_stats["mode"] == "stream"
    assert ds.ingest_stats["sketch_exact"] is True
    # streamed file datasets never keep a raw matrix
    assert ds.raw_data is None


def test_chunk_boundary_determinism(tmp_path):
    X, y = _make_data(n=3000)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    models = []
    mats = []
    for chunk in (1000, 7000, 256):
        b, ds = _train_env(csv, "stream", chunk)
        models.append(b.model_to_string())
        mats.append(np.asarray(ds.binned.bins).copy())
    assert models[0] == models[1] == models[2]
    np.testing.assert_array_equal(mats[0], mats[1])
    np.testing.assert_array_equal(mats[0], mats[2])


def test_stream_binned_matrix_matches_construct_binned():
    X, y = _make_data(n=2000, F=4)
    ds = lgb.Dataset(X, label=y, params={**PARAMS,
                                         "ingest_mode": "stream",
                                         "ingest_chunk_rows": 333})
    ds.construct()
    ref = lgb.Dataset(X, label=y, params=dict(PARAMS)).construct()
    np.testing.assert_array_equal(np.asarray(ds.binned.bins),
                                  np.asarray(ref.binned.bins))
    for a, b in zip(ds.binned.bin_mappers, ref.binned.bin_mappers):
        np.testing.assert_array_equal(a.upper_bounds, b.upper_bounds)


def test_stream_sequence_and_arrow_sources():
    X, y = _make_data(n=1500, F=4)

    class Seq(lgb.Sequence):
        batch_size = 256

        def __getitem__(self, idx):
            return X[idx]

        def __len__(self):
            return len(X)

    p = {**PARAMS, "ingest_mode": "stream", "ingest_chunk_rows": 400}
    ds = lgb.Dataset(Seq(), label=y, params=p)
    ds.construct()
    ref = lgb.Dataset(X, label=y, params=dict(PARAMS)).construct()
    np.testing.assert_array_equal(np.asarray(ds.binned.bins),
                                  np.asarray(ref.binned.bins))
    pa = pytest.importorskip("pyarrow")
    tbl = pa.table({f"f{i}": X[:, i] for i in range(X.shape[1])})
    ds_a = lgb.Dataset(tbl, label=y, params=p)
    ds_a.construct()
    np.testing.assert_array_equal(np.asarray(ds_a.binned.bins),
                                  np.asarray(ref.binned.bins))


def test_stream_categorical_and_zero_as_missing(tmp_path):
    rng = np.random.RandomState(4)
    n = 3000
    X = np.column_stack([
        np.round(rng.randn(n), 2),
        rng.choice([0, 1, 2, 3, 7], n).astype(float),
        np.where(rng.rand(n) < 0.4, 0.0, np.round(rng.randn(n), 2)),
    ])
    y = (X[:, 0] + (X[:, 1] == 2) > 0).astype(float)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    extra = {"categorical_feature": [1], "zero_as_missing": True}
    b_in, _ = _train_env(csv, "inmem", extra=extra)
    b_st, _ = _train_env(csv, "stream", 500, extra=extra)
    assert b_in.model_to_string() == b_st.model_to_string()


def test_stream_valid_set_binned_with_reference(tmp_path):
    X, y = _make_data(n=2500)
    Xv, yv = _make_data(n=800, seed=19)
    tr_csv = _write_csv(tmp_path / "tr.csv", X, y)
    va_csv = _write_csv(tmp_path / "va.csv", Xv, yv)
    p = {**PARAMS, "ingest_mode": "stream", "ingest_chunk_rows": 600}
    ds = lgb.Dataset(tr_csv, params=p)
    vs = lgb.Dataset(va_csv, reference=ds, params=p)
    bst = lgb.train(p, ds, num_boost_round=5, valid_sets=[vs])
    assert bst.num_trees() == 5
    # valid set binned with the TRAINING mappers
    for a, b in zip(vs.binned.bin_mappers, ds.binned.bin_mappers):
        np.testing.assert_array_equal(np.asarray(a.upper_bounds),
                                      np.asarray(b.upper_bounds))


def test_auto_mode_resolution(tmp_path):
    X, y = _make_data(n=200)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    assert resolve_ingest_mode({}, csv) == "inmem"          # small file
    assert resolve_ingest_mode({"ingest_mode": "stream"}, csv) == "stream"
    assert resolve_ingest_mode({"ingest_cache": "auto"}, csv) == "stream"
    with pytest.raises(LightGBMError):
        resolve_ingest_mode({"ingest_mode": "bogus"}, csv)


def test_libsvm_falls_back_to_inmem(tmp_path):
    path = tmp_path / "t.libsvm"
    rng = np.random.RandomState(1)
    path.write_text("\n".join(
        f"{rng.randint(0, 2)} " + " ".join(
            f"{j}:{rng.rand():.3f}" for j in range(4))
        for _ in range(300)))
    ds = lgb.Dataset(str(path), params={"ingest_mode": "stream",
                                        "verbosity": -1})
    ds.construct()          # in-memory fallback, no crash
    assert ds.binned is not None and ds.num_data_ == 300


# ---------------------------------------------------------------------------
# Binned cache
# ---------------------------------------------------------------------------

def test_cache_roundtrip_bit_identical_and_memmap(tmp_path):
    X, y = _make_data()
    csv = _write_csv(tmp_path / "t.csv", X, y)
    extra = {"ingest_cache": "auto"}
    b1, d1 = _train_env(csv, "stream", 700, extra=extra)
    assert d1.ingest_stats.get("cache_written")
    b2, d2 = _train_env(csv, "stream", 700, extra=extra)
    assert d2.ingest_stats["cache_hit"] is True
    assert b1.model_to_string() == b2.model_to_string()
    assert isinstance(d2.binned.bins, np.memmap)
    # raw-vs-cache: also identical to the plain inmem loader (same
    # params in both arms; LGBTPU_INGEST=inmem bypasses the cache)
    b3, d3 = _train_env(csv, "inmem", extra=extra)
    assert d3.ingest_stats is None
    assert b3.model_to_string() == b1.model_to_string()


def test_cache_restores_metadata_without_raw_file(tmp_path):
    X, y = _make_data(n=1200)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    w = np.random.RandomState(0).rand(1200) + 0.5
    (tmp_path / "t.csv.weight").write_text(
        "\n".join(f"{v:.6f}" for v in w))
    extra = {"ingest_cache": "auto"}
    _, d1 = _train_env(csv, "stream", 500, extra=extra)
    _, d2 = _train_env(csv, "stream", 500, extra=extra)
    assert d2.ingest_stats["cache_hit"] is True
    np.testing.assert_allclose(d2.get_weight(), w, rtol=1e-6)
    np.testing.assert_array_equal(d2.get_label(), d1.get_label())


@pytest.mark.parametrize("corrupt,field", [
    ("truncate", "magic"),
    ("garbage", "magic"),
    ("version", "format_version"),
    ("tear", "col_sha256"),
])
def test_cache_corruption_raises_structured_error(tmp_path, corrupt, field):
    X, y = _make_data(n=1000)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    _train_env(csv, "stream", 500, extra={"ingest_cache": "auto"})
    cpath = csv + ".lgbcache"
    blob = bytearray(open(cpath, "rb").read())
    if corrupt == "truncate":
        blob = blob[:8]
    elif corrupt == "garbage":
        blob = b"GARBAGEGARBAGEGA" + bytes(blob[16:])
    elif corrupt == "version":
        blob = b"LGBTPU.CACHE.v9\n" + bytes(blob[16:])
    elif corrupt == "tear":
        blob[40] = blob[40] ^ 0xFF      # flip a bins byte
    open(cpath, "wb").write(bytes(blob))
    with pytest.raises(LightGBMError, match=field):
        _train_env(csv, "stream", 500, extra={"ingest_cache": "read"})
    # auto falls back to raw parsing and rewrites a fresh cache
    b, d = _train_env(csv, "stream", 500, extra={"ingest_cache": "auto"})
    assert d.ingest_stats["cache_hit"] is False
    assert d.ingest_stats.get("cache_written")
    b2, d2 = _train_env(csv, "stream", 500, extra={"ingest_cache": "auto"})
    assert d2.ingest_stats["cache_hit"] is True
    assert b.model_to_string() == b2.model_to_string()


def test_cache_read_requires_existing_cache(tmp_path):
    X, y = _make_data(n=600)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    with pytest.raises(LightGBMError, match="no binned cache"):
        _train_env(csv, "stream", 500, extra={"ingest_cache": "read"})


def test_cache_params_hash_mismatch(tmp_path):
    X, y = _make_data(n=1000)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    _train_env(csv, "stream", 500, extra={"ingest_cache": "auto"})
    with pytest.raises(LightGBMError, match="params_hash"):
        _train_env(csv, "stream", 500,
                   extra={"ingest_cache": "read", "max_bin": 63})
    # data change invalidates too (source signature feeds the hash)
    _write_csv(tmp_path / "t.csv", X + 1.0, y)
    b, d = _train_env(csv, "stream", 500, extra={"ingest_cache": "auto"})
    assert d.ingest_stats["cache_hit"] is False


# ---------------------------------------------------------------------------
# Checkpoint/resume + device ship + memory hygiene
# ---------------------------------------------------------------------------

def test_checkpoint_resume_bit_identity_from_stream(tmp_path):
    X, y = _make_data(n=2500)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    out = str(tmp_path / "m.txt")
    extra = {"snapshot_freq": 4, "output_model": out}
    full, _ = _train_env(csv, "stream", 600, extra=extra, rounds=10)
    snap = str(tmp_path / "m.txt.snapshot_iter_4")
    assert os.path.exists(snap)
    os.environ["LGBTPU_INGEST"] = "stream"
    os.environ["LGBTPU_INGEST_CHUNK"] = "600"
    try:
        p = {**PARAMS, **extra}
        resumed = lgb.train(p, lgb.Dataset(csv, params=p),
                            num_boost_round=10, resume_from=snap)
    finally:
        os.environ.pop("LGBTPU_INGEST", None)
        os.environ.pop("LGBTPU_INGEST_CHUNK", None)
    assert resumed.model_to_string() == full.model_to_string()


def test_chunked_device_ship_matches_oneshot():
    from lightgbm_tpu.device_data import ship_binned_chunks, to_device
    rng = np.random.RandomState(0)
    bins = rng.randint(0, 17, (1000, 3)).astype(np.uint8)
    os.environ["LGBTPU_INGEST_SHIP"] = "1"
    try:
        arr = ship_binned_chunks(bins, n_pad=1024, chunk_rows=300)
    finally:
        os.environ.pop("LGBTPU_INGEST_SHIP", None)
    assert arr.shape == (1024, 3)
    np.testing.assert_array_equal(np.asarray(arr[:1000]), bins)
    np.testing.assert_array_equal(np.asarray(arr[1000:]), 0)


def test_file_dataset_frees_raw_after_train(tmp_path):
    X, y = _make_data(n=800)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    ds = lgb.Dataset(csv, params={"verbosity": -1})     # inmem path
    # construct() alone keeps raw_data: lgb.cv's subset() folds and the
    # linear-tree fitter still read it after construct
    ds.construct()
    assert ds.raw_data is not None
    # once a Booster owns the binned data, the raw matrix (largest host
    # allocation) is dropped
    lgb.train({"objective": "binary", "verbosity": -1}, ds,
              num_boost_round=1)
    assert ds.raw_data is None
    # in-memory containers keep their raw data (get_data contract)
    ds2 = lgb.Dataset(X, label=y, params={"verbosity": -1})
    lgb.train({"objective": "binary", "verbosity": -1}, ds2,
              num_boost_round=1)
    assert ds2.get_data() is not None
    # explicit opt-out wins
    ds3 = lgb.Dataset(csv, params={"verbosity": -1}, free_raw_data=False)
    lgb.train({"objective": "binary", "verbosity": -1}, ds3,
              num_boost_round=1)
    assert ds3.raw_data is not None
    # linear_tree keeps raw: the leaf fitter reads raw feature values
    ds4 = lgb.Dataset(csv, params={"verbosity": -1})
    lgb.train({"objective": "binary", "verbosity": -1,
               "linear_tree": True}, ds4, num_boost_round=1)
    assert ds4.raw_data is not None


def test_ingest_telemetry_gauges_and_spans(tmp_path):
    from lightgbm_tpu import telemetry
    X, y = _make_data(n=1500)
    csv = _write_csv(tmp_path / "t.csv", X, y)
    telemetry.reset()
    telemetry.configure(enabled=True)
    try:
        _train_env(csv, "stream", 400, rounds=2)
        snap = telemetry.global_registry.snapshot()
        gauges = snap.get("gauges", {})
        assert gauges.get("ingest/rows_per_s", 0) > 0
        assert gauges.get("ingest/peak_rss_bytes", 0) > 0
        names = {e.get("name") for e in telemetry.global_tracer.events}
        assert "ingest/pass1" in names and "ingest/pass2" in names
        assert "ingest/chunk" in names
    finally:
        telemetry.configure(enabled=False, metrics_out="", trace_out="")
        telemetry.reset()


def test_construct_binned_matches_bin_rows_into_chunks():
    """bin_rows_into (the preallocated-buffer chunk fill both streaming
    paths use) is byte-identical to construct_binned, bundles included."""
    from lightgbm_tpu.binning import (bin_rows_into, binned_layout,
                                      find_bin_mappers,
                                      find_feature_groups)
    rng = np.random.RandomState(8)
    n = 2000
    X = np.zeros((n, 6))
    X[:, 0] = rng.randn(n)
    # mutually exclusive sparse columns -> zero EFB conflicts -> bundles
    owner = rng.randint(1, 6, n)
    active = rng.rand(n) < 0.6
    X[np.arange(n)[active], owner[active]] = rng.randn(int(active.sum()))
    mappers = find_bin_mappers(X, max_bin=63, min_data_in_bin=3)
    sample_bins = [mappers[f].transform(X[:, f]) for f in range(6)]
    groups = find_feature_groups(sample_bins, mappers, enable_bundle=True)
    assert any(len(g) > 1 for g in groups), "fixture should bundle"
    ref = construct_binned(X, mappers, groups)
    (og, _, _, fo, _, dtype) = binned_layout(mappers, groups)
    out = np.empty((n, len(og)), dtype)
    for s in range(0, n, 321):
        bin_rows_into(X[s:s + 321], mappers, og, out, s)
    np.testing.assert_array_equal(out, ref.bins)
    np.testing.assert_array_equal(fo, ref.feature_offsets)


# ---------------------------------------------------------------------------
# Distributed streaming ingest (2 real jax.distributed processes)
# ---------------------------------------------------------------------------

_DIST_CHILD = r"""
import os, sys
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
try:  # cross-process CPU collectives (older jax: option absent)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
port, rank, data, out = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=rank)
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import lightgbm_tpu as lgb
os.environ["LGBTPU_INGEST"] = "stream"
os.environ["LGBTPU_INGEST_CHUNK"] = "700"
ds = lgb.Dataset(data)
bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                 "min_data_in_leaf": 5, "tree_learner": "data",
                 "hist_backend": "stream"},
                ds, num_boost_round=5)
assert ds._dist is not None and ds._dist["nproc"] == 2
assert ds.ingest_stats["mode"] == "stream"
assert ds.ingest_stats["sketch_exact"] is True
# each rank parsed ONLY its shard
assert ds.ingest_stats["rows"] < 4000
if rank == 0:
    open(out, "w").write(bst.model_to_string())
"""


@pytest.mark.slow
def test_two_process_stream_ingest(tmp_path,
                                   require_two_process_collectives):
    """Each rank streams only its byte shard; the ONE-collective sketch
    sync must yield the same mappers — and structurally the same model —
    as a single-process streamed run over the whole file."""
    import pathlib
    import socket
    import subprocess
    import sys as _sys
    repo = pathlib.Path(__file__).resolve().parent.parent
    rng = np.random.RandomState(0)
    Xd = rng.randn(4000, 6)
    yd = (Xd[:, 0] + np.sin(Xd[:, 1]) + 0.1 * rng.randn(4000) > 0)
    data = str(tmp_path / "train.csv")
    np.savetxt(data, np.column_stack([yd.astype(float), Xd]),
               delimiter=",", fmt="%.10g")
    out = str(tmp_path / "dist_model.txt")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{repo}:" + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [_sys.executable, "-c", _DIST_CHILD, str(port), str(r), data, out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-4000:]}"

    os.environ["LGBTPU_INGEST"] = "stream"
    os.environ["LGBTPU_INGEST_CHUNK"] = "700"
    try:
        ref_ds = lgb.Dataset(data)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1, "min_data_in_leaf": 5,
                         "hist_backend": "stream"},
                        ref_ds, num_boost_round=5)
    finally:
        os.environ.pop("LGBTPU_INGEST", None)
        os.environ.pop("LGBTPU_INGEST_CHUNK", None)
    dist_model = open(out).read()
    # same comparison discipline as test_dist_ingest: structural identity
    # with float tolerance (serial-vs-data f32 summation order)
    a = bst.model_to_string().split("\nparameters:")[0].splitlines()
    b = dist_model.split("\nparameters:")[0].splitlines()
    assert len(a) == len(b)
    for xa, xb in zip(a, b):
        if xa == xb:
            continue
        ka, _, va = xa.partition("=")
        kb, _, vb = xb.partition("=")
        assert ka == kb
        if ka == "tree_sizes":
            continue
        fa = np.array([float(t) for t in va.split()])
        fb = np.array([float(t) for t in vb.split()])
        np.testing.assert_allclose(fa, fb, rtol=3e-4, atol=3e-4,
                                   err_msg=ka)
