"""Chunked/arrow ingestion, prediction early stop, distributed launcher.

Reference: c_api.h LGBM_DatasetCreateFromMats (chunked mats),
include/LightGBM/arrow.h, src/boosting/prediction_early_stop.cpp,
python-package/lightgbm/dask.py (launcher analog)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _model(n=2000, seed=4, k=1):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    if k == 1:
        y = (X[:, 0] + X[:, 1] > 0).astype(float)
        obj = {"objective": "binary"}
    else:
        y = np.clip((X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5), 0, k - 1)
        obj = {"objective": "multiclass", "num_class": k}
        y = y.astype(float)
    bst = lgb.train({**obj, "num_leaves": 15, "verbosity": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    return bst, X, y


def test_chunked_ingestion_matches_single():
    rs = np.random.RandomState(3)
    X = rs.randn(1500, 5)
    y = X @ rs.rand(5)
    chunks = [X[:500], X[500:900], X[900:]]
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5}
    b1 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
    b2 = lgb.train(p, lgb.Dataset(chunks, label=y), num_boost_round=5)
    assert b1.model_to_string() == b2.model_to_string()


def test_pyarrow_table_ingestion():
    pa = pytest.importorskip("pyarrow")
    rs = np.random.RandomState(5)
    X = rs.randn(800, 4)
    y = X[:, 0] * 2 + 0.1 * rs.randn(800)
    table = pa.table({f"f{i}": X[:, i] for i in range(4)})
    ds = lgb.Dataset(table, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    ds, num_boost_round=5)
    assert ds.feature_name() == ["f0", "f1", "f2", "f3"]
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.9


def test_pred_early_stop_binary():
    bst, X, y = _model()
    p_full = bst.predict(X, raw_score=True)
    p_es = bst.predict(X, raw_score=True, pred_early_stop=True,
                       pred_early_stop_freq=5, pred_early_stop_margin=2.0)
    # confident rows freeze early: same SIGN almost everywhere; a frozen
    # row's 2*|partial score| exceeded the margin at some checkpoint
    # (reference prediction_early_stop.cpp:66: margin = 2*fabs(pred) >
    # margin_threshold), so its magnitude may legitimately differ
    assert ((p_es > 0) == (p_full > 0)).mean() > 0.98
    # (1e-6: p_es may come from a different predictor path than p_full,
    # so unfrozen rows agree only to float noise)
    frozen = np.abs(p_es - p_full) > 1e-6
    if frozen.any():
        assert 2.0 * np.abs(p_es[frozen]).min() > 2.0
    # a tiny margin must cut more tree evaluations than a huge one: proxy via
    # difference from the full prediction
    p_tiny = bst.predict(X, raw_score=True, pred_early_stop=True,
                         pred_early_stop_freq=1, pred_early_stop_margin=0.01)
    assert np.abs(p_tiny - p_full).mean() >= np.abs(p_es - p_full).mean()


def test_pred_early_stop_multiclass():
    bst, X, y = _model(k=3)
    p_full = bst.predict(X)
    p_es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=3,
                       pred_early_stop_margin=3.0)
    assert (p_full.argmax(1) == p_es.argmax(1)).mean() > 0.97


def test_init_distributed_single_process():
    # single-process call either succeeds (process_count 1 after init) or
    # raises the library error — never an unhandled backend exception
    try:
        lgb.init_distributed(num_processes=1, process_id=0,
                             coordinator_address="127.0.0.1:41999")
    except lgb.LightGBMError:
        pass


def test_pyarrow_columnar_binning_matches_dense():
    """The Arrow columnar path (binning straight from column buffers, no
    dense matrix) must produce bit-identical bins to dense ingestion."""
    pa = pytest.importorskip("pyarrow")
    rs = np.random.RandomState(9)
    X = rs.randn(1200, 5)
    X[::7, 1] = np.nan
    y = X[:, 0] + 0.1 * rs.randn(1200)
    table = pa.table({f"c{i}": X[:, i] for i in range(5)})
    ds_a = lgb.Dataset(table, label=y)
    ds_d = lgb.Dataset(X, label=y)
    ds_a.construct(), ds_d.construct()
    assert ds_a.raw_arrow is not None or ds_a.binned is not None
    np.testing.assert_array_equal(np.asarray(ds_a.binned.bins),
                                  np.asarray(ds_d.binned.bins))
    assert ds_a.binned.group_features == ds_d.binned.group_features


def test_pyarrow_multichunk_never_materializes_column():
    """Chunk-bounded Arrow ingest (reference: include/LightGBM/arrow.h
    ArrowChunkedArray): a multi-chunk table bins chunk-by-chunk — sampling,
    mapper search and binning all read per-producer-chunk slices, and the
    full float64 column/matrix is never coalesced. Bins must still be
    bit-identical to dense ingestion."""
    pa = pytest.importorskip("pyarrow")
    rs = np.random.RandomState(3)
    n = 1500
    X = rs.randn(n, 4)
    X[::11, 2] = np.nan
    y = X[:, 0] + 0.1 * rs.randn(n)
    # 5 uneven producer chunks per column
    bounds = [0, 100, 471, 900, 1337, n]
    cols = {}
    for i in range(4):
        cols[f"c{i}"] = pa.chunked_array(
            [X[bounds[j]:bounds[j + 1], i] for j in range(5)])
    table = pa.table(cols)
    assert table.column(0).num_chunks == 5

    ds_a = lgb.Dataset(table, label=y)
    # spy on the chunk accessor: every piece handed to binning must be a
    # producer chunk, never a coalesced full column
    sizes = []
    orig = lgb.Dataset._arrow_col_chunks

    def spy(self, f):
        for start, vals in orig(self, f):
            sizes.append(len(vals))
            yield start, vals
    lgb.Dataset._arrow_col_chunks = spy
    try:
        ds_a.construct()
    finally:
        lgb.Dataset._arrow_col_chunks = orig
    max_chunk = max(b - a for a, b in zip(bounds, bounds[1:]))
    assert sizes and max(sizes) == max_chunk < n
    ds_d = lgb.Dataset(X, label=y)
    ds_d.construct()
    np.testing.assert_array_equal(np.asarray(ds_a.binned.bins),
                                  np.asarray(ds_d.binned.bins))
    # and the model trains from the chunked dataset
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(table, label=y), num_boost_round=3)
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.8


def test_pandas_categorical_alignment_roundtrip():
    """Predict-time DataFrames with a DIFFERENT category order (or unseen
    categories) must remap through the TRAINING category lists, in memory
    and through a model-file round trip (reference: _data_from_pandas +
    pandas_categorical in the model text)."""
    pd = pytest.importorskip("pandas")
    rs = np.random.RandomState(2)
    n = 1200
    colors = rs.choice(["red", "green", "blue", "violet"], n)
    x1 = rs.randn(n)
    y = ((colors == "red") | (x1 > 0.8)).astype(np.float64)
    df = pd.DataFrame({
        "c": pd.Categorical(colors, categories=["red", "green", "blue",
                                                "violet"]),
        "x": x1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(df, label=y, categorical_feature=["c"]),
                    num_boost_round=8)
    base = bst.predict(df, raw_score=True)

    # same VALUES, different category-list order + an unseen category
    df2 = pd.DataFrame({
        "c": pd.Categorical(colors, categories=["violet", "blue", "green",
                                                "red", "black"]),
        "x": x1})
    np.testing.assert_allclose(bst.predict(df2, raw_score=True), base,
                               rtol=1e-12)

    # model-file round trip carries the mapping
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst2.predict(df2, raw_score=True), base,
                               rtol=1e-12)

    # unseen category routes like a missing value, not like category 0
    df3 = pd.DataFrame({
        "c": pd.Categorical(["black"] * 4, categories=["black"]),
        "x": np.zeros(4)})
    p_unseen = bst.predict(df3, raw_score=True)
    df_nan = pd.DataFrame({
        "c": pd.Categorical([None] * 4, categories=["red"]),
        "x": np.zeros(4)})
    np.testing.assert_allclose(p_unseen, bst.predict(df_nan, raw_score=True),
                               rtol=1e-12)
