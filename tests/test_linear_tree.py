"""Linear trees (reference: src/treelearner/linear_tree_learner.cpp,
arxiv 1802.05640 Eq 3; model grammar src/io/tree.cpp:384-408)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _linear_data(n=2000, seed=4):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 5)
    # piecewise-linear target: trees with linear leaves fit this much better
    y = np.where(X[:, 0] > 0, 3.0 * X[:, 1] + 1.0, -2.0 * X[:, 1]) \
        + 0.05 * rs.randn(n)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 20, "learning_rate": 0.2}


def test_linear_tree_beats_constant_leaves():
    X, y = _linear_data()
    d1 = lgb.Dataset(X, label=y)
    const = lgb.train(PARAMS, d1, num_boost_round=10)
    d2 = lgb.Dataset(X, label=y)
    lin = lgb.train({**PARAMS, "linear_tree": True}, d2, num_boost_round=10)
    mse_c = float(np.mean((const.predict(X) - y) ** 2))
    mse_l = float(np.mean((lin.predict(X) - y) ** 2))
    assert mse_l < mse_c * 0.7, (mse_l, mse_c)
    # trees after the first carry real linear models
    trees = lin._all_trees()
    assert trees[0].is_linear
    assert any(any(len(c) > 0 for c in t.leaf_coeff) for t in trees[1:])


def test_linear_tree_model_roundtrip(tmp_path):
    X, y = _linear_data(seed=6)
    bst = lgb.train({**PARAMS, "linear_tree": True},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    p1 = bst.predict(X)
    path = str(tmp_path / "linear.txt")
    bst.save_model(path)
    txt = open(path).read()
    assert "is_linear=1" in txt
    assert "leaf_const=" in txt and "leaf_coeff=" in txt
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), p1, rtol=1e-6, atol=1e-8)


def test_linear_tree_nan_fallback():
    X, y = _linear_data(seed=8)
    Xn = X.copy()
    bst = lgb.train({**PARAMS, "linear_tree": True},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    Xn[:50, 1] = np.nan
    p = bst.predict(Xn)
    assert np.isfinite(p).all()


def test_linear_tree_guards():
    X, y = _linear_data(seed=9)
    with pytest.raises(lgb.LightGBMError):
        lgb.train({**PARAMS, "linear_tree": True, "boosting": "dart"},
                  lgb.Dataset(X, label=y), num_boost_round=2)
