"""CLI multi-machine wiring (reference: the parallel_learning example conf:
num_machines + machine_list_file, python-package/lightgbm/dask.py:196-215
machine assembly, src/network/linkers_socket.cpp:83 find-own-rank).

A localhost-simulated 2-"host" run: two processes each execute the REAL CLI
entry (`lightgbm_tpu.cli.main`) on the same conf with their own
local_listen_port; each locates its rank in the machine list, connects via
jax.distributed, ingests its row shard, and trains the same SPMD program.
The resulting model must match single-process CLI training on the full
file."""
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

_CHILD = r"""
import os, sys
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
workdir, port = sys.argv[1], sys.argv[2]
os.chdir(workdir)
from lightgbm_tpu import cli
rc = cli.main(["config=train.conf", f"local_listen_port={port}"])
assert rc == 0
"""


def _free_ports(k):
    socks = [socket.socket() for _ in range(k)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.mark.slow
def test_two_machine_cli_matches_single(tmp_path,
                                        require_two_process_collectives):
    rng = np.random.RandomState(0)
    n = 3000
    X = rng.randn(n, 5)
    y = (X[:, 0] + np.sin(X[:, 1]) > 0).astype(float)
    data = tmp_path / "train.csv"
    np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.10g")

    p0, p1 = _free_ports(2)
    conf_body = (
        "task = train\nobjective = binary\ndata = train.csv\n"
        "num_trees = 5\nnum_leaves = 15\nmin_data_in_leaf = 5\n"
        "tree_learner = data\nhist_backend = stream\nverbosity = -1\n"
        "num_machines = 2\nmachine_list_file = mlist.txt\n")

    # single-process reference run (no machines keys)
    single = tmp_path / "single"
    single.mkdir()
    (single / "train.csv").symlink_to(data)
    (single / "train.conf").write_text(conf_body.replace(
        "num_machines = 2\nmachine_list_file = mlist.txt\n", ""))
    env = {"PYTHONPATH": str(REPO)}
    import os
    env.update({k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")})
    env["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get(
        "PYTHONPATH", "")
    out = subprocess.run([sys.executable, "-c", _CHILD, str(single), "12400"],
                         env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr

    # two "machines", each in its own working dir with its own port
    procs = []
    dirs = []
    for rank, port in ((0, p0), (1, p1)):
        d = tmp_path / f"m{rank}"
        d.mkdir()
        (d / "train.csv").symlink_to(data)
        (d / "train.conf").write_text(conf_body)
        (d / "mlist.txt").write_text(
            f"127.0.0.1 {p0}\n127.0.0.1 {p1}\n")
        dirs.append(d)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(d), str(port)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = [p.communicate(timeout=900)[0].decode() for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o

    from test_dist_ingest import _models_structurally_equal

    ref = (single / "LightGBM_model.txt").read_text()
    for d in dirs:
        got = (d / "LightGBM_model.txt").read_text()
        # identical split structure; leaf sums differ ~1e-7 (two-shard
        # psum association vs one shard), like the dist-ingest suite
        _models_structurally_equal(got, ref)
