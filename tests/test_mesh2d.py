"""2D mesh (rows x feature-groups) training tests — docs/DISTRIBUTED.md
"2D mesh".

tree_learner=data with mesh_shape="data:R,feature:F" runs ONE shard_map
over BOTH axes: histograms build shard-locally on each device's feature-
group slice (zero feature-axis collective) and psum_scatter over the row
axis down to G/(R*F) groups per device; the split scan runs on that slice
through the ShardPlan sub-FeatureLayout machinery, and best-split records
all_gather over both axes with the exact (max gain, lowest global feature
id) tie-break.  Every per-row array stays sharded over rows ONLY and
replicated over the feature axis.

Identity discipline (PR 6): the round-1 tree matches serial BYTE-for-byte
(low-mantissa round-1 gradients make every f32 summation order exact);
later rounds match structurally with ulp tolerance (the psum_scatter
reduction order differs from the serial accumulation).  Runs on the
conftest 8-device CPU mesh and the 4-device 2x2 tier run_all_tests.sh
adds.
"""
import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import global_registry, launch_count
from lightgbm_tpu.utils.log import LightGBMError

from conftest import make_synthetic_binary, make_synthetic_multiclass

N_DEV = len(jax.devices())
MESHES_2D = [(r, f) for r, f in ((2, 2), (2, 4)) if r * f <= N_DEV]
needs_mesh = pytest.mark.skipif(N_DEV < 4, reason="needs a >=4-device mesh")


def _strip_params(model_str: str) -> str:
    return model_str.split("\nparameters:")[0]


def _assert_2d_identity(a: str, b: str):
    """Round-1 byte equality + full structural identity with ulp-tolerant
    float fields (the PR 6 non-associativity discipline)."""
    a, b = _strip_params(a), _strip_params(b)
    ta, tb = a.split("Tree="), b.split("Tree=")
    assert len(ta) == len(tb)
    assert ta[1] == tb[1], "round-1 tree must match serial byte-for-byte"
    la, lb = a.splitlines(), b.splitlines()
    assert len(la) == len(lb)
    for xa, xb in zip(la, lb):
        if xa == xb:
            continue
        ka, _, va = xa.partition("=")
        kb, _, vb = xb.partition("=")
        assert ka == kb, f"{ka!r} != {kb!r}"
        if ka == "tree_sizes":    # byte lengths of the float reprs
            continue
        fa = np.array([float(t) for t in va.split()])
        fb = np.array([float(t) for t in vb.split()])
        np.testing.assert_allclose(fa, fb, rtol=3e-4, atol=3e-4,
                                   err_msg=ka)


def _train(params, X, y, rounds=4, mesh=None, **ds_kw):
    p = dict(params, verbosity=-1)
    if mesh is not None:
        r, f = mesh
        p.update(tree_learner="data", mesh_shape=f"data:{r},feature:{f}")
    bst = lgb.train(p, lgb.Dataset(X, label=y, **ds_kw),
                    num_boost_round=rounds)
    if mesh is not None:
        eng = bst.engine
        assert eng._mesh_2d and not eng._mesh_stream
        assert eng._row_axis == "data" and eng._feature_axis == "feature"
    return bst


def _2d_vs_serial(params, X, y, rounds=4, mesh=(2, 2), **ds_kw):
    s = _train(params, X, y, rounds, None, **ds_kw)
    m = _train(params, X, y, rounds, mesh, **ds_kw)
    _assert_2d_identity(s.model_to_string(), m.model_to_string())
    return m


# ---------------------------------------------------------------------------
# end-to-end identity vs serial on 2x2 and 2x4
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize("mesh", MESHES_2D,
                         ids=[f"{r}x{f}" for r, f in MESHES_2D])
def test_2d_identity_binary(mesh):
    X, y = make_synthetic_binary(n=2000, f=8)
    _2d_vs_serial({"objective": "binary", "num_leaves": 15,
                   "min_data_in_leaf": 5}, X, y, mesh=mesh)


@needs_mesh
@pytest.mark.parametrize("mesh", MESHES_2D,
                         ids=[f"{r}x{f}" for r, f in MESHES_2D])
def test_2d_identity_bagging(mesh):
    X, y = make_synthetic_binary(n=2000, f=8)
    _2d_vs_serial({"objective": "binary", "num_leaves": 15,
                   "min_data_in_leaf": 5, "bagging_fraction": 0.7,
                   "bagging_freq": 2, "seed": 3}, X, y, rounds=5, mesh=mesh)


@needs_mesh
@pytest.mark.parametrize("mesh", MESHES_2D,
                         ids=[f"{r}x{f}" for r, f in MESHES_2D])
def test_2d_identity_goss(mesh):
    """GOSS on the 2D mesh: the global top-rate threshold reduces over the
    row axis only (per-row |g| arrays are feature-replicated), sampling
    runs as exact zero-weight dense masking (no compaction on 2D).

    Identity discipline for GOSS (docs/DISTRIBUTED.md "2D mesh"): the
    UNSAMPLED warmup rounds match serial byte-for-byte, every tree keeps
    the identical shape, and quality stays at parity — the top-rate cut
    is a discrete threshold on ulp-drifted gradients, so a borderline
    row may legitimately flip in-bag after warmup (the same reason the
    1D stream mesh never claimed serial byte-identity for GOSS)."""
    X, y = make_synthetic_binary(n=2000, f=8)
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "data_sample_strategy": "goss", "learning_rate": 0.5,
         "top_rate": 0.2, "other_rate": 0.2}
    f = _train(p, X, y, rounds=5, mesh=mesh)
    assert f.engine._fused_last
    assert f.engine._last_compact_rows == 0, \
        "2D mesh must not engage row compaction"
    assert f.engine._last_sampled_rows > 0
    s = _train(p, X, y, rounds=5)
    ts = _strip_params(s.model_to_string()).split("Tree=")[1:]
    tf = _strip_params(f.model_to_string()).split("Tree=")[1:]
    warmup = 2   # 1 / learning_rate unsampled iterations
    for i in range(warmup):
        assert ts[i] == tf[i], \
            f"warmup tree {i} must match serial byte-for-byte"
    for i, (a, b) in enumerate(zip(ts, tf)):
        assert len(a.splitlines()) == len(b.splitlines()), \
            f"tree {i} shape diverged from serial"
    acc_s = np.mean((np.asarray(s.predict(X)) > 0.5) == y)
    acc_f = np.mean((np.asarray(f.predict(X)) > 0.5) == y)
    assert acc_f >= acc_s - 0.02


@needs_mesh
@pytest.mark.parametrize("mesh", MESHES_2D,
                         ids=[f"{r}x{f}" for r, f in MESHES_2D])
def test_2d_identity_multiclass_batched(mesh):
    """All K class trees grow in lockstep through the 2D grow_tree_k
    path — histograms stack the K channel inside the same shard_map."""
    X, y = make_synthetic_multiclass(n=2000, f=8, k=3)
    m = _2d_vs_serial({"objective": "multiclass", "num_class": 3,
                       "num_leaves": 11, "min_data_in_leaf": 5},
                      X, y, rounds=3, mesh=mesh)
    assert m.engine._mc_batched_last


# ---------------------------------------------------------------------------
# placement + dispatch invariants
# ---------------------------------------------------------------------------

@needs_mesh
def test_2d_state_placement():
    """Bins shard over BOTH axes; every per-row array shards over rows
    only (spec names the data axis, never the feature axis) — P('data')
    on the 2D mesh replicates over 'feature' automatically."""
    X, y = make_synthetic_binary(n=2000, f=8)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y,
                 mesh=(2, 2))
    eng = bst.engine
    bins_spec = tuple(eng.dd.bins.sharding.spec)
    assert bins_spec == ("data", "feature"), bins_spec
    st = eng._train_state
    assert st is not None and st.score is eng.score
    for name in ("score", "grad", "hess", "leaf_id", "mask"):
        spec = tuple(getattr(st, name).sharding.spec)
        assert "data" in spec, f"state.{name} lost its row sharding"
        assert "feature" not in spec, \
            f"state.{name} must replicate over the feature axis: {spec}"
    assert tuple(st.finished.sharding.spec) == ()


@needs_mesh
def test_2d_fused_single_launch_per_iteration():
    """The fused path must engage on the 2D mesh and stay at ONE
    watched_jit launch per steady-state iteration."""
    X, y = make_synthetic_binary(n=2000, f=8)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y,
                 rounds=2, mesh=(2, 2))
    assert bst.engine._fused_last, "fused path did not engage on 2D"
    l0 = launch_count()
    for _ in range(4):
        bst.update()
    launches = (launch_count() - l0) / 4
    assert launches <= 1.5, f"2D fused path dispatched {launches}/iter"


@needs_mesh
def test_2d_backend_resolution_and_stream_rejected():
    """2D resolves to a contraction backend (stream cannot slice its
    row-major packed group words over the feature axis) and an explicit
    stream request fails loudly."""
    X, y = make_synthetic_binary(n=800, f=8)
    bst = _train({"objective": "binary", "num_leaves": 7}, X, y,
                 rounds=1, mesh=(2, 2))
    assert bst.engine._grow_params.hist_backend in ("segsum", "onehot")
    assert not bst.engine._grow_params.int_hist
    with pytest.raises(LightGBMError, match="stream"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "tree_learner": "data",
                   "mesh_shape": "data:2,feature:2",
                   "hist_backend": "stream"},
                  lgb.Dataset(X, label=y), num_boost_round=1)
    with pytest.raises(LightGBMError, match="monotone"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "tree_learner": "data",
                   "mesh_shape": "data:2,feature:2",
                   "monotone_constraints": [1] + [0] * 7},
                  lgb.Dataset(X, label=y), num_boost_round=1)


# ---------------------------------------------------------------------------
# 2D analytic comms model vs telemetry (satellite: hist_comms_bytes 2D)
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize(
    "extra", [{}, {"hist_packed_width": 16, "use_quantized_grad": True}],
    ids=["default", "packed16"])
def test_2d_comms_gauge_matches_analytic_model(extra):
    """comms/hist_bytes_per_round must equal the 2D analytic model on
    2x2: row-axis scatter of each device's G/F block down to G/(R*F)
    groups + both-axes record gather, feature-axis histogram bytes ZERO.
    hist_packed_width rides the int-stream wire, which 2D cannot use —
    the wire stays 4-byte f32 and the gauge must NOT change."""
    from lightgbm_tpu.parallel.comms import hist_comms_bytes_per_round

    X, y = make_synthetic_binary(n=1500, f=8)
    global_registry.reset()
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "tree_learner": "data", "mesh_shape": "data:2,feature:2",
         "telemetry": True}
    p.update(extra)
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=2)
    eng = bst.engine
    cm = eng._comms_model()
    assert cm["mode"] == "2d"
    assert cm["devices"] == 4 and cm["d_rows"] == 2 and cm["d_feat"] == 2
    assert cm["packed_width"] == 32   # packed wire never applies on 2D
    gp = eng._grow_params
    S = min(gp.max_splits_per_round, max(gp.num_leaves - 1, 1))
    expected = hist_comms_bytes_per_round(
        S, eng.dd.num_groups, eng.dd.max_bins, 2, "reduce_scatter",
        "f32", num_class=1, packed_width=32, d_feat=2)
    assert cm["per_round_bytes"] == expected
    snap = global_registry.snapshot()
    assert snap["gauges"]["comms/hist_bytes_per_round"] == expected
    assert snap["counters"]["comms/hist_bytes"] > 0
    # the scatter slice scales down ~R*F-fold vs the replicated psum block
    psum_block = hist_comms_bytes_per_round(
        S, eng.dd.num_groups, eng.dd.max_bins, 4, "psum")
    assert expected * 2 < psum_block


# ---------------------------------------------------------------------------
# mesh construction error paths for the newly legal 2D shapes
# ---------------------------------------------------------------------------

def test_2d_mesh_shape_rejects_oversized_product():
    """Axis product beyond the device count fails loudly with the
    required total."""
    from lightgbm_tpu.parallel.mesh import create_mesh
    need = 2 * N_DEV
    with pytest.raises(LightGBMError,
                       match=f"needs {need} devices, have {N_DEV}"):
        create_mesh(f"data:2,feature:{N_DEV}", "data")


def test_2d_mesh_shape_rejects_zero_axis():
    """Zero/negative axis sizes raise naming the offending axis part."""
    from lightgbm_tpu.parallel.mesh import create_mesh, parse_mesh_shape
    for spec, bad in [("data:0,feature:2", "data:0"),
                      ("data:2,feature:0", "feature:0"),
                      ("data:2,feature:-1", "feature:-1")]:
        with pytest.raises(LightGBMError, match="non-positive size"):
            parse_mesh_shape(spec)
        try:
            create_mesh(spec, "data")
            raise AssertionError("create_mesh accepted " + spec)
        except LightGBMError as e:
            assert bad in str(e), (spec, str(e))


@needs_mesh
def test_2d_mesh_only_data_learner():
    """Only tree_learner=data consumes both axes; the other learners
    still refuse a combined mesh (the refusal now points at the 2D
    spelling instead of claiming 2-axis sharding is unsupported)."""
    from lightgbm_tpu.parallel.mesh import create_mesh
    m = create_mesh("data:2,feature:2", "data")
    assert m is not None and m.shape == {"data": 2, "feature": 2}
    for learner in ("serial", "feature", "voting"):
        with pytest.raises(LightGBMError, match="2-axis"):
            create_mesh("data:2,feature:2", learner)
    # unknown second axes stay refused even for tree_learner=data
    with pytest.raises(LightGBMError, match="2-axis"):
        create_mesh("data:2,model:2", "data")
    # trailing size-1 axes remain harmless for sweep tooling
    m1 = create_mesh("data:2,feature:1", "data")
    assert m1 is not None and m1.shape == {"data": 2, "feature": 1}
