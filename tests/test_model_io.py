"""Model text-format tests (model: reference test_engine.py save/load + golden format)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import make_synthetic_binary, make_synthetic_multiclass, \
    make_synthetic_regression


def test_model_string_structure():
    X, y = make_synthetic_binary()
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    s = bst.model_to_string()
    # LightGBM v4 text format landmarks
    assert s.startswith("tree\n")
    for key in ("version=v4", "num_class=1", "num_tree_per_iteration=1",
                "max_feature_idx=9", "objective=binary sigmoid:1",
                "feature_names=", "feature_infos=", "tree_sizes=",
                "Tree=0", "num_leaves=", "split_feature=", "threshold=",
                "decision_type=", "left_child=", "right_child=", "leaf_value=",
                "internal_value=", "shrinkage=", "end of trees",
                "feature_importances:", "parameters:", "end of parameters"):
        assert key in s, f"missing {key!r} in model string"


def test_roundtrip_exact_predictions():
    X, y = make_synthetic_regression()
    bst = lgb.train({"objective": "regression", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    s = bst.model_to_string()
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-6, atol=1e-7)


def test_multiclass_roundtrip():
    X, y = make_synthetic_multiclass()
    bst = lgb.train({"objective": "multiclass", "num_class": 4, "verbosity": -1,
                     "num_leaves": 7}, lgb.Dataset(X, label=y), num_boost_round=4)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    assert bst2.num_model_per_iteration() == 4
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-5, atol=1e-6)


def test_categorical_roundtrip():
    rs = np.random.RandomState(4)
    n = 2000
    cat = rs.randint(0, 6, n).astype(np.float64)
    x1 = rs.randn(n)
    effect = np.array([1.0, -2.0, 0.5, 2.0, -1.0, 3.0])
    y = effect[cat.astype(int)] + 0.1 * rs.randn(n)
    X = np.column_stack([cat, x1])
    bst = lgb.train({"objective": "regression", "verbosity": -1, "num_leaves": 7,
                     "min_data_per_group": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=5)
    s = bst.model_to_string()
    assert "num_cat=" in s
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-5, atol=1e-6)


def test_dump_model_json():
    X, y = make_synthetic_regression()
    bst = lgb.train({"objective": "regression", "verbosity": -1, "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    d = bst.dump_model()
    assert d["num_class"] == 1
    assert len(d["tree_info"]) == 3
    t0 = d["tree_info"][0]["tree_structure"]
    assert "split_feature" in t0
    assert "left_child" in t0
    # walk: every path ends in a leaf
    def depth(node):
        if "leaf_index" in node:
            return 0
        return 1 + max(depth(node["left_child"]), depth(node["right_child"]))
    assert depth(t0) >= 1


def test_num_iteration_predict():
    X, y = make_synthetic_regression()
    bst = lgb.train({"objective": "regression", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    p5 = bst.predict(X, num_iteration=5)
    p10 = bst.predict(X, num_iteration=10)
    assert not np.allclose(p5, p10)
    # fewer trees = worse fit generally
    assert np.mean((p10 - y) ** 2) <= np.mean((p5 - y) ** 2) + 1e-6


def test_pred_leaf_and_contrib():
    X, y = make_synthetic_regression(n=400)
    bst = lgb.train({"objective": "regression", "verbosity": -1, "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    leaves = bst.predict(X, pred_leaf=True)
    assert leaves.shape == (len(y), 3)
    assert leaves.max() < 7
    contrib = bst.predict(X[:50], pred_contrib=True)
    assert contrib.shape == (50, X.shape[1] + 1)
    pred = bst.predict(X[:50], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), pred, rtol=1e-4, atol=1e-4)
