"""Batched multiclass growth (ops.grow.grow_tree_k).

The widened lockstep path — one histogram contraction per growth round
serving all K class trees' gradient channels — must produce trees
bit-identical to the per-class lax.scan path (LGBTPU_MULTICLASS_BATCHED=1/0
A/B), stay serial-vs-data-parallel consistent, and trace exactly once.
Satellite regressions (one-row multiclass .init files, the packed-predictor
cache, seeded shuffle_models) ride along.
"""
import os
import re

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _mc_data(n=800, f=8, k=4, seed=7):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    logits = np.stack([X[:, i % f] + 0.5 * X[:, (i + 1) % f]
                       for i in range(k)], axis=1)
    y = np.argmax(logits + rs.randn(n, k) * 0.5, axis=1).astype(np.float64)
    return X, y


def _train_str(X, y, k, rounds=6, **extra):
    params = {"objective": "multiclass", "num_class": k, "num_leaves": 15,
              "verbosity": -1, "min_data_in_leaf": 5, "max_bin": 63, **extra}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds)
    return bst.model_to_string()


def _strip_params(s):
    """Drop the parameters dump (records e.g. tree_learner name)."""
    return s.split("\nparameters:")[0]


def _structure(s):
    return (re.findall(r"split_feature=([^\n]*)", s),
            re.findall(r"\nthreshold=([^\n]*)", s))


def _leaf_values(s):
    return [np.array([float(v) for v in line.split()])
            for line in re.findall(r"leaf_value=([^\n]*)", s)]


@pytest.mark.parametrize("objective", ["multiclass", "multiclassova"])
def test_batched_bit_identical_to_scan(objective, monkeypatch):
    """The widened path's trees must be BIT-IDENTICAL to the per-class
    scan path's (acceptance criterion of the batched-growth redesign)."""
    X, y = _mc_data()
    monkeypatch.setenv("LGBTPU_MULTICLASS_BATCHED", "1")
    a = _train_str(X, y, 4, objective=objective)
    monkeypatch.setenv("LGBTPU_MULTICLASS_BATCHED", "0")
    b = _train_str(X, y, 4, objective=objective)
    assert a == b


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.slow
def test_batched_matches_scan_stream_backend(quantized, monkeypatch):
    """Stream backend A/B (pallas kernel in interpret mode on CPU): the
    widened kernel contracts (m_rows, 2*S*K) columns where the scan path
    contracts (m_rows, 2*S) per class. On the MXU each output column's
    systolic reduction is independent of the operand's column count; CPU
    interpret mode runs Eigen f32 dots whose reduction order is NOT
    column-count-independent, so values get a one-ulp tolerance here while
    the tree structure must match exactly."""
    X, y = _mc_data(n=400, f=6, k=3)
    extra = {"hist_backend": "stream", "num_leaves": 8, "max_bin": 31}
    if quantized:
        extra.update(use_quantized_grad=True, num_grad_quant_bins=64)
    monkeypatch.setenv("LGBTPU_MULTICLASS_BATCHED", "1")
    a = _train_str(X, y, 3, rounds=3, **extra)
    monkeypatch.setenv("LGBTPU_MULTICLASS_BATCHED", "0")
    b = _train_str(X, y, 3, rounds=3, **extra)
    assert _structure(a) == _structure(b)
    for va, vb in zip(_leaf_values(a), _leaf_values(b)):
        np.testing.assert_allclose(va, vb, rtol=1e-4, atol=5e-6)


@pytest.mark.slow
def test_batched_matches_scan_stream_bucketed(monkeypatch):
    """Bucketed one-hot M-axis + K channels: low-cardinality features give
    the stream kernel a bucketed layout, whose per-run unflatten gains a
    class axis on the widened path."""
    rs = np.random.RandomState(3)
    n, k = 400, 3
    # >= 8 groups per bucket: the bucketed M-axis only beats uniform once
    # the 8-group sublane padding amortizes (gbdt._resolved_bin_buckets)
    X = np.column_stack([rs.randn(n, 8),
                         rs.randint(0, 5, (n, 16)).astype(np.float64)])
    y = (np.argmax(np.stack([X[:, i] + X[:, 8 + i] for i in range(k)], 1)
                   + rs.randn(n, k), axis=1).astype(np.float64))
    extra = {"hist_backend": "stream", "num_leaves": 8, "max_bin": 63}

    def train(force):
        monkeypatch.setenv("LGBTPU_MULTICLASS_BATCHED", force)
        params = {"objective": "multiclass", "num_class": k, "num_leaves": 8,
                  "verbosity": -1, "min_data_in_leaf": 5, **extra}
        bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
        return bst

    ba = train("1")
    assert ba.engine._grow_params.bin_buckets is not None  # layout engaged
    a = ba.model_to_string()
    b = train("0").model_to_string()
    assert _structure(a) == _structure(b)
    for va, vb in zip(_leaf_values(a), _leaf_values(b)):
        np.testing.assert_allclose(va, vb, rtol=1e-4, atol=5e-6)


def test_multiclass_serial_vs_data_parallel():
    """Multiclass trees from the row-sharded mesh must equal the serial
    run's (the widened program's histogram reduce under GSPMD is exact —
    the reference's ReduceScatter property, test_tree_equality extended to
    the K-class path)."""
    X, y = _mc_data(n=1200, f=8, k=3, seed=5)
    s = _train_str(X, y, 3, rounds=4, tree_learner="serial")
    d = _train_str(X, y, 3, rounds=4, tree_learner="data")
    assert _strip_params(s) == _strip_params(d)


def test_batched_path_traces_once():
    """watched_jit telemetry: ONE grow_tree_k trace for the whole run (no
    K-per-shape retraces — the per-iteration cost target depends on it)."""
    import lightgbm_tpu.telemetry as tel
    X, y = _mc_data(n=400, f=6, k=3)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 8,
              "verbosity": -1, "min_data_in_leaf": 5, "max_bin": 31,
              "telemetry": True}
    try:
        bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
        rec = bst.telemetry_summary().get("recompiles", {})
        assert "grow_tree_k" in rec
        # the summary aggregates every LIVE entry of the name (other
        # models in the process may hold their own); no single model may
        # have traced the widened grower more than once
        assert rec["grow_tree_k"]["max_per_entry"] == 1
    finally:
        tel.configure(enabled=False, metrics_out="", trace_out="")
        tel.reset()


def test_one_row_multiclass_init_score(tmp_path):
    """A one-row multiclass .init file must keep its (1, num_class) shape
    (np.loadtxt squeezes to (num_class,) without ndmin=2)."""
    from lightgbm_tpu.dataset_io import load_init_score_file
    base = tmp_path / "train.txt"
    base.write_text("1 0.5 0.25\n")
    (tmp_path / "train.txt.init").write_text("0.1 0.2 0.7\n")
    arr = load_init_score_file(str(base))
    assert arr.shape == (1, 3)
    np.testing.assert_allclose(arr[0], [0.1, 0.2, 0.7])
    # a one-column multirow file stays 1-D (regression init scores)
    (tmp_path / "train.txt.init").write_text("0.1\n0.2\n0.3\n")
    arr = load_init_score_file(str(base))
    assert arr.shape == (3,)


def test_fast_predict_cache_rebinds_on_leaf_mutation():
    """The packed single-row predictor must invalidate when a tree's
    leaf_value array is REBOUND (DART shrink / set_leaf_output), and must
    be reused while the model is untouched. The cache holds strong
    references compared with `is` — id() recycling cannot false-hit."""
    X, y = _mc_data(n=300, f=5, k=3)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 6,
              "verbosity": -1, "min_data_in_leaf": 5, "max_bin": 31}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=2)
    row = X[:1]
    p1 = bst.predict(row, raw_score=True)
    pred1 = bst._fast1_cache[2]
    bst.predict(row, raw_score=True)
    assert bst._fast1_cache[2] is pred1          # unchanged model: reused
    t = bst._all_trees()[0]
    lv = np.asarray(t.leaf_value, np.float64).copy() + 1.0
    t.leaf_value = lv                            # rebind without Booster API
    p2 = bst.predict(row, raw_score=True)
    assert bst._fast1_cache[2] is not pred1      # rebind invalidates
    assert not np.allclose(p1, p2)


def test_shuffle_models_seeded_and_rng_isolated():
    """shuffle_models must permute deterministically (seeded local RNG) and
    leave the global numpy RNG stream untouched (reproducible refit
    pipelines)."""
    X, y = _mc_data(n=300, f=5, k=3)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 6,
              "verbosity": -1, "min_data_in_leaf": 5, "max_bin": 31}

    def fresh():
        return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)

    b1, b2 = fresh(), fresh()
    np.random.seed(123)
    before = np.random.rand(4)
    np.random.seed(123)
    b1.shuffle_models()
    b2.shuffle_models()
    after = np.random.rand(4)
    np.testing.assert_array_equal(before, after)   # global RNG untouched
    assert b1.model_to_string() == b2.model_to_string()
    # the permutation actually changed tree order for this seed
    assert b1.model_to_string() != fresh().model_to_string()
