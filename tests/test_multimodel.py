"""Multi-tenant serving (docs/SERVING.md "Multi-tenant serving").

The multi-model cache contract under test:

  * model-id routing is bitwise: every tenant serves exactly its own
    file-loaded ``Booster.predict``, through the registry, the stacked
    dispatch path, HTTP ``/predict`` and ``/explain``;
  * same-shape tenants SHARE compiled programs — mixed-tenant stacked
    dispatch after warmup traces nothing new;
  * LRU eviction under the HBM byte budget drops only device arrays:
    readmission rebuilds from the manifest-verified file (a tampered
    file is refused), in-flight requests pinned to an evicting model
    drain on their old reference (the hot-reload drain contract,
    extended to the evict path);
  * per-model SLO/drift isolation: one tenant's burn or poisoned reload
    names only that tenant in ``/ready``; siblings stay green;
  * fleet promotion is keyed ``(model_id, generation)``: per-tenant
    pointer files with independent counters, filtered history, and
    tenant-scoped rollback.
"""
import http.client
import json
import os
import shutil
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (MicroBatcher, MultiModelRegistry,
                                  ServingApp, parse_model_roster)
from lightgbm_tpu.telemetry import recompile_counts


def _make_data(seed=7, n=500):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    X[:, 4] = rs.randint(0, 9, n)
    X[rs.rand(n) < 0.15, 0] = np.nan
    y = ((X[:, 1] > 0) ^ (X[:, 4] == 3)).astype(np.float64)
    return X, y


def _train_to_file(path, seed=3):
    X, y = _make_data(seed)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "seed": seed}
    ds = lgb.Dataset(X, label=y, categorical_feature=[4])
    bst = lgb.train(params, ds, num_boost_round=6)
    bst.save_model(str(path))
    return X


@pytest.fixture(scope="module")
def tenants(tmp_path_factory):
    """(paths, X, refs) — three same-shape tenants plus a replacement
    candidate for beta; references are FILE-loaded boosters (the bytes
    the server actually serves)."""
    td = tmp_path_factory.mktemp("multimodel")
    paths, refs = {}, {}
    X = None
    for i, mid in enumerate(("alpha", "beta", "gamma")):
        p = td / f"{mid}.txt"
        X = _train_to_file(p, seed=3 + i)
        paths[mid] = str(p)
        refs[mid] = lgb.Booster(model_file=str(p))
    p2 = td / "beta_v2.txt"
    _train_to_file(p2, seed=31)
    paths["beta_v2"] = str(p2)
    refs["beta_v2"] = lgb.Booster(model_file=str(p2))
    return paths, X, refs


@pytest.fixture(scope="module")
def multiapp(tenants):
    """One warmed multi-tenant ServingApp shared by the HTTP tests."""
    paths, X, refs = tenants
    roster = {m: paths[m] for m in ("alpha", "beta", "gamma")}
    app = ServingApp("", models=roster, port=0, max_batch=32,
                     max_delay_ms=1.0, queue_size=256,
                     explain_max_batch=16).start()
    yield app, X, refs
    app.shutdown(drain=True)


def _post(host, port, path, obj, timeout=15):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(obj),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def _get(host, port, path, timeout=15):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# roster + config
# ---------------------------------------------------------------------------

def test_parse_model_roster():
    r = parse_model_roster("a=/tmp/a.txt, b=/tmp/b.txt")
    assert list(r) == ["a", "b"]
    assert parse_model_roster({"x": "p"}) == {"x": "p"}
    for bad in ("justapath", "a=", "=p", "a=p,a=q", "bad id=p", ""):
        with pytest.raises(lgb.LightGBMError):
            parse_model_roster(bad)


def test_config_roster_validation(tenants):
    from lightgbm_tpu.config import Config
    paths, _, _ = tenants
    spec = f"a={paths['alpha']},b={paths['beta']}"
    cfg = Config.from_params({"serve_models": spec,
                              "serve_default_model": "b"})
    assert cfg.serve_models == spec
    # alias
    cfg = Config.from_params({"model_roster": spec})
    assert cfg.serve_models == spec
    with pytest.raises(lgb.LightGBMError):
        Config.from_params({"serve_models": "nope"})
    with pytest.raises(lgb.LightGBMError, match="default"):
        Config.from_params({"serve_models": spec,
                            "serve_default_model": "zz"})
    with pytest.raises(lgb.LightGBMError):
        Config.from_params({"serve_models": spec,
                            "serve_hbm_budget_mb": -1})


# ---------------------------------------------------------------------------
# routing + shared-program stacked dispatch
# ---------------------------------------------------------------------------

def test_multi_registry_routing_bitwise(multiapp):
    app, X, refs = multiapp
    reg = app.registry
    for mid in ("alpha", "beta", "gamma"):
        got = reg.current(mid).raw_scores(X[:9])
        want = refs[mid].predict(X[:9], raw_score=True)
        assert np.array_equal(got, want), mid
    with pytest.raises(lgb.LightGBMError, match="unknown model_id"):
        reg.current("nope")


def test_stacked_dispatch_zero_recompiles_bitwise(multiapp):
    """Mixed-tenant windows dispatch as ONE stacked program; after the
    boot warmup no bucket/slot combination traces anything new."""
    app, X, refs = multiapp
    reg = app.registry
    # prime: one grouped window so any lazy path is already traced
    jobs = [(reg.current(m), X[:8]) for m in ("alpha", "beta", "gamma")]
    reg.raw_scores_grouped(jobs)
    before = dict(recompile_counts())
    for rows in (X[:3], X[:8], X[10:26]):
        jobs = [(reg.current(m), rows) for m in ("alpha", "beta", "gamma")]
        outs = reg.raw_scores_grouped(jobs)
        for (model, r), got in zip(jobs, outs):
            want = refs[model.model_id].predict(r, raw_score=True)
            assert np.array_equal(got, want), model.model_id
    after = dict(recompile_counts())
    assert after == before, f"stacked dispatch recompiled: {before} -> {after}"


# ---------------------------------------------------------------------------
# LRU eviction + manifest-verified readmission
# ---------------------------------------------------------------------------

def test_lru_evict_readmit_bitwise(tenants, tmp_path):
    paths, X, refs = tenants
    local = {m: str(tmp_path / f"{m}.txt") for m in ("alpha", "beta")}
    for m, p in local.items():
        shutil.copy(paths[m], p)
        sidecar = paths[m] + ".quality.json"
        if os.path.exists(sidecar):
            shutil.copy(sidecar, p + ".quality.json")
    reg = MultiModelRegistry(local, max_batch=8, warmup=False)
    one = reg.current("alpha").device_bytes()
    reg.budget_bytes = int(one * 1.5)    # room for ONE resident model
    reg.current("beta")                  # readmits beta, evicts alpha
    st = reg.stats()
    assert st["cache"]["resident"] == ["beta"]
    assert reg.evictions >= 1
    # readmission rebuilds from the file and stays bitwise
    got = reg.current("alpha").raw_scores(X[:7])
    assert np.array_equal(got, refs["alpha"].predict(X[:7], raw_score=True))
    assert reg.readmissions >= 1
    assert reg.stats()["cache"]["resident"] == ["alpha"]
    # a tampered file is refused at readmission (manifest re-verify)
    reg.current("beta")                  # beta resident, alpha evicted
    with open(local["alpha"], "r+") as fh:
        data = fh.read()
        fh.seek(0)
        fh.truncate()
        fh.write(data[: len(data) // 2])
    with pytest.raises(lgb.LightGBMError):
        reg.current("alpha")
    # beta is untouched by alpha's corruption
    got = reg.current("beta").raw_scores(X[:7])
    assert np.array_equal(got, refs["beta"].predict(X[:7], raw_score=True))


def test_evict_path_inflight_drain(multiapp):
    """The hot-reload drain contract on the EVICT path: requests pinned
    at submit drain bitwise on their old reference while the tenant is
    evicted and readmitted under traffic."""
    app, X, refs = multiapp
    b = MicroBatcher(app.registry, max_batch=32, max_delay_ms=1.0,
                     queue_size=256).start()
    stop = threading.Event()
    errs, out = [], []

    def client(seed):
        rs = np.random.RandomState(seed)
        while not stop.is_set():
            s = rs.randint(0, 400)
            m = int(rs.choice([1, 3, 7]))
            try:
                f = b.submit(X[s:s + m], raw_score=True, model_id="gamma")
                out.append((s, m, f.result(timeout=10)))
            except Exception as e:  # noqa: BLE001 — collected for assert
                errs.append(e)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(5):
            app.registry.tenant("gamma").evict()   # mid-traffic eviction
            stop.wait(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join(10)
        b.stop()
    assert not errs, errs[:3]
    assert len(out) > 10
    want = refs["gamma"].predict(X[:410], raw_score=True)
    for s, m, res in out:
        assert res.model_id == "gamma"
        assert np.array_equal(res.values, want[s:s + m]), f"rows {s}:{s+m}"
    assert app.registry.tenant("gamma").evictions >= 5


# ---------------------------------------------------------------------------
# HTTP: /predict + /explain routing
# ---------------------------------------------------------------------------

def test_http_model_id_routing_bitwise(multiapp):
    app, X, refs = multiapp
    for mid in ("alpha", "beta", "gamma"):
        code, obj = _post(app.host, app.port, "/predict",
                          {"rows": X[:11].tolist(), "model_id": mid})
        assert code == 200
        assert obj["model_id"] == mid
        assert np.array_equal(np.asarray(obj["predictions"]),
                              refs[mid].predict(X[:11]))
    # default tenant: first roster entry
    code, obj = _post(app.host, app.port, "/predict",
                      {"rows": X[:4].tolist()})
    assert code == 200
    assert np.array_equal(np.asarray(obj["predictions"]),
                          refs["alpha"].predict(X[:4]))
    code, obj = _post(app.host, app.port, "/predict",
                      {"rows": X[:4].tolist(), "model_id": "nope"})
    assert code == 400
    assert "unknown model_id" in obj["error"]


def test_http_explain_pred_contrib_contract(multiapp):
    """/explain returns per-feature contributions + expected value,
    bitwise equal to ``Booster.predict(pred_contrib=True)``."""
    app, X, refs = multiapp
    for mid, m in (("alpha", 5), ("beta", 3)):
        code, obj = _post(app.host, app.port, "/explain",
                          {"rows": X[:m].tolist(), "model_id": mid})
        assert code == 200, obj
        assert obj["model_id"] == mid
        want = refs[mid].predict(X[:m], pred_contrib=True)
        assert np.array_equal(np.asarray(obj["contributions"]), want), mid
    # explain lane surfaces its own counters
    code, st = _get(app.host, app.port, "/stats")
    assert code == 200
    assert st["explain"]["served"] >= 2


# ---------------------------------------------------------------------------
# per-model SLO / degradation isolation
# ---------------------------------------------------------------------------

def test_per_model_slo_isolation(multiapp):
    """One tenant's error-budget burn names only that tenant in /ready;
    siblings stay green (the isolation contract)."""
    app, X, refs = multiapp
    mon = app.slo_by_model["beta"]
    try:
        for _ in range(60):
            mon.record(500, 5.0)
        mon.tick()
        code, obj = _get(app.host, app.port, "/ready")
        assert code == 200
        models = obj["models"]
        assert "slo_alert" in models["beta"]
        assert "slo_alert" not in models["alpha"]
        assert "slo_alert" not in models["gamma"]
        assert "model beta" in obj.get("degraded", "")
        assert "model alpha" not in obj.get("degraded", "")
    finally:
        # drain the burn so later tests see a clean monitor
        for _ in range(2000):
            mon.record(200, 1.0)
        mon.tick()


def test_per_model_drift_isolation(multiapp):
    """A drift alert on one tenant's quality monitor marks only that
    tenant's /ready record; sibling tenants carry no drift_alert."""
    app, X, refs = multiapp
    q = app.quality_by_model.get("gamma")
    if q is None:
        pytest.skip("quality monitors disabled in this build")
    q.alerting = True
    try:
        code, obj = _get(app.host, app.port, "/ready")
        assert code == 200
        models = obj["models"]
        assert models["gamma"].get("drift_alert") is True
        assert "drift_alert" not in models["alpha"]
        assert "drift_alert" not in models["beta"]
        assert "model gamma" in obj.get("degraded", "")
        assert "model alpha" not in obj.get("degraded", "")
    finally:
        q.alerting = False


def test_poisoned_reload_isolated_to_tenant(multiapp, tmp_path):
    """A truncated candidate for one tenant is refused registry-locally;
    the tenant keeps serving its old bytes and siblings never notice."""
    app, X, refs = multiapp
    bad = tmp_path / "poison.txt"
    data = open(app.registry.tenant("beta").current().path).read()
    bad.write_text(data[: len(data) // 2])
    code, obj = _post(app.host, app.port, "/reload",
                      {"path": str(bad), "model_id": "beta"})
    assert code in (400, 409)
    for mid in ("alpha", "beta", "gamma"):
        code, obj = _post(app.host, app.port, "/predict",
                          {"rows": X[:6].tolist(), "model_id": mid})
        assert code == 200
        assert np.array_equal(np.asarray(obj["predictions"]),
                              refs[mid].predict(X[:6])), mid
    # model_id reload without multi-tenant serving is a structured 400
    code, obj = _post(app.host, app.port, "/reload",
                      {"path": str(bad), "model_id": "zz"})
    assert code in (400, 409)


def test_tenant_reload_leaves_siblings_bitwise(multiapp, tenants):
    """Promotion of one tenant (registry-local /reload) swaps only that
    tenant; sibling responses stay bitwise across the swap."""
    app, X, refs = multiapp
    paths, _, _ = tenants
    pre = {}
    for mid in ("alpha", "gamma"):
        _, obj = _post(app.host, app.port, "/predict",
                       {"rows": X[:9].tolist(), "model_id": mid})
        pre[mid] = np.asarray(obj["predictions"])
    code, obj = _post(app.host, app.port, "/reload",
                      {"path": paths["beta_v2"], "model_id": "beta"})
    assert code == 200, obj
    assert obj.get("model_id") == "beta"
    _, obj = _post(app.host, app.port, "/predict",
                   {"rows": X[:9].tolist(), "model_id": "beta"})
    assert np.array_equal(np.asarray(obj["predictions"]),
                          refs["beta_v2"].predict(X[:9]))
    for mid in ("alpha", "gamma"):
        _, obj = _post(app.host, app.port, "/predict",
                       {"rows": X[:9].tolist(), "model_id": mid})
        assert np.array_equal(np.asarray(obj["predictions"]), pre[mid]), mid
    # restore beta for any later test using this module fixture
    code, _ = _post(app.host, app.port, "/reload",
                    {"path": paths["beta"], "model_id": "beta"})
    assert code == 200


# ---------------------------------------------------------------------------
# per-tenant promotion pointers (no fleet processes: pointer unit tests)
# ---------------------------------------------------------------------------

def test_per_tenant_pointer_keying(tenants, tmp_path):
    from lightgbm_tpu.serving.fleet import (generation_history,
                                            pointer_name, promote_pointer,
                                            read_pointer, rollback_pointer)
    paths, _, _ = tenants
    fdir = str(tmp_path)
    pa = promote_pointer(fdir, paths["alpha"], model_id="a")
    pb = promote_pointer(fdir, paths["beta"], model_id="b")
    flat = promote_pointer(fdir, paths["gamma"])
    # independent per-tenant generation counters
    assert pa["generation"] == 1 and pb["generation"] == 1
    assert flat["generation"] == 1
    assert pa["model_id"] == "a" and "model_id" not in flat
    assert os.path.exists(os.path.join(fdir, pointer_name("a")))
    p2 = promote_pointer(fdir, paths["beta_v2"], model_id="b")
    assert p2["generation"] == 2
    assert read_pointer(fdir, "a")["generation"] == 1    # sibling untouched
    assert read_pointer(fdir)["generation"] == 1         # flat untouched
    # history: interleaved trail, per-tenant filter
    assert [h["generation"] for h in generation_history(fdir, "b")] == [1, 2]
    assert len(generation_history(fdir)) == 4
    assert [h["generation"] for h in generation_history(fdir, "")] == [1]
    # tenant-scoped rollback (sibling + flat counters stay put)
    rb = rollback_pointer(fdir, reason="test", model_id="b")
    assert rb["generation"] == 1 and rb["rollback_from"] == 2
    assert read_pointer(fdir, "b")["path"] == paths["beta"]
    assert read_pointer(fdir, "a")["generation"] == 1
    with pytest.raises(lgb.LightGBMError):
        pointer_name("bad id")
    with pytest.raises(lgb.LightGBMError):
        rollback_pointer(fdir, model_id="a")   # no prior generation
