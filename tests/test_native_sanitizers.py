"""Sanitizer tier for the native host kernels (SURVEY aux subsystems:
race/memory-error detection; reference analog: the ASAN/UBSAN CI lanes the
C++ reference runs on its OpenMP code).

Builds native/binner.cpp with -fsanitize=address,undefined into a
standalone harness that exercises every extern-C entry point (CSV shape
scan + parse, value_to_bin with NaN/missing variants, the multi-tree
single-row walker incl. a categorical bitset split) and asserts a clean
exit — any out-of-bounds read/write, leak, or UB aborts the binary."""
import os
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SRC = REPO / "lightgbm_tpu" / "native" / "binner.cpp"

_MAIN = r"""
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <vector>

extern "C" {
void lgbt_rows_cols(const char*, int64_t, char, int, int64_t*, int64_t*);
void lgbt_parse_csv(const char*, int64_t, char, int, int64_t, int64_t,
                    double*);
void lgbt_value_to_bin(const double*, int64_t, const double*, int32_t,
                       int32_t, int32_t, int32_t, uint16_t*);
void lgbt_predict_row(const double*, const int32_t*, int32_t,
                      const int32_t*, const double*, const int32_t*,
                      const uint8_t*, const int32_t*, const int32_t*,
                      const int32_t*, const double*, const int32_t*,
                      const uint32_t*, int32_t, double*);
}

int main() {
  // CSV parse incl. header skip, a RAGGED short row (NaN-fill path) and
  // a final line WITHOUT a trailing newline (EOF boundary scan)
  const char* csv = "a,b,c\n1,2.5,nan\n4,-5e-1\n7,8,9";
  int64_t rows = 0, cols = 0;
  lgbt_rows_cols(csv, (int64_t)strlen(csv), ',', 1, &rows, &cols);
  if (rows != 3 || cols != 3) return 1;
  std::vector<double> out((size_t)rows * cols);
  lgbt_parse_csv(csv, (int64_t)strlen(csv), ',', 1, rows, cols, out.data());
  if (out[0] != 1.0 || out[4] != -0.5) return 2;
  if (!std::isnan(out[5]) || out[8] != 9.0) return 3;   // ragged fill + EOF row

  // value_to_bin across missing types, incl. NaN and boundary values
  std::vector<double> vals = {-1e30, -1.0, 0.0, 0.5, 1.0, 1e30,
                              std::nan("")};
  std::vector<double> ub = {-0.5, 0.25, 0.75, 1e300};
  std::vector<uint16_t> bins(vals.size());
  for (int mt = 0; mt <= 2; ++mt)
    lgbt_value_to_bin(vals.data(), (int64_t)vals.size(), ub.data(),
                      (int32_t)ub.size(), mt, 5, 1, bins.data());

  // three-tree walk: numeric split w/ NaN default-left + two categorical
  // bitset splits, the second exercising the WORD-INDEX edge of the bitset
  // walker (iv/32 selecting word 0/1, the last set bit 63, the first
  // out-of-range category 64, and a far-out-of-range 1e12 — each must be
  // an in-bounds read of cat_threshold or a clean go-right, never a read
  // past the ordinal's [s, e) word span; UBSan/ASan abort otherwise)
  // tree 0: 1 internal node (feature 0 <= 0.5), leaves -0.5 / 0.5
  // tree 1: categorical on feature 1, ONE-word bitset holding category 3
  // tree 2: categorical on feature 1, TWO-word bitset (ordinal 1) holding
  //         categories 32 (word 1, bit 0) and 63 (word 1, bit 31)
  std::vector<int32_t> tree_off = {0, 1, 2, 3};
  std::vector<int32_t> split_feature = {0, 1, 1};
  std::vector<double> threshold = {0.5, 0.0, 0.0};
  std::vector<int32_t> threshold_bin = {0, 0, 1};   // cat ordinals
  std::vector<uint8_t> decision_type = {(uint8_t)(2 | (2 << 2)),
                                        (uint8_t)1, (uint8_t)1};
  std::vector<int32_t> left = {~0, ~0, ~0}, right = {~1, ~1, ~1};
  std::vector<int32_t> leaf_off = {0, 2, 4};
  std::vector<double> leaf_value = {-0.5, 0.5, -2.0, 2.0, -8.0, 8.0};
  std::vector<int32_t> cat_boundaries = {0, 1, 3};
  std::vector<uint32_t> cat_threshold = {1u << 3,          // ordinal 0
                                         0u,               // ord 1 word 0
                                         1u | (1u << 31)}; // ord 1 word 1
  double rowvals[8][2] = {{0.0, 3.0}, {1.0, 3.0},
                          {std::nan(""), 7.0}, {0.2, -1.0},
                          {0.0, 32.0},   // word boundary: first bit, word 1
                          {0.0, 63.0},   // last bit of the last word
                          {0.0, 64.0},   // first category past the span
                          {0.0, 1e12}};  // way past: iv/32 >> e - s
  double expect[8] = {
      -0.5 + -2.0 + 8.0,  // cat 3: tree1 left, tree2 word0 bit3 unset
      0.5 + -2.0 + 8.0,   // 1.0 > 0.5 right; cat 3 -> left / right
      -0.5 + 2.0 + 8.0,   // NaN numeric -> default_left; cat 7 unset
      -0.5 + 2.0 + 8.0,   // 0.2 left; cat -1 (negative) -> right
      -0.5 + 2.0 + -8.0,  // cat 32: tree1 word span is 1 -> right,
                          //         tree2 word 1 bit 0 set -> left
      -0.5 + 2.0 + -8.0,  // cat 63: tree2 word 1 bit 31 set -> left
      -0.5 + 2.0 + 8.0,   // cat 64: word 2 outside span -> right
      -0.5 + 2.0 + 8.0};  // cat 1e12: far outside every span -> right
  for (int r = 0; r < 8; ++r) {
    double acc[1] = {0.0};
    lgbt_predict_row(rowvals[r], tree_off.data(), 3, split_feature.data(),
                     threshold.data(), threshold_bin.data(),
                     decision_type.data(), left.data(), right.data(),
                     leaf_off.data(), leaf_value.data(),
                     cat_boundaries.data(), cat_threshold.data(), 1, acc);
    if (std::fabs(acc[0] - expect[r]) > 1e-12) return 10 + r;
  }
  puts("sanitizer harness OK");
  return 0;
}
"""


@pytest.mark.slow
def test_native_asan_ubsan(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain")
    probe = subprocess.run(
        ["g++", "-fsanitize=address,undefined", "-x", "c++", "-", "-o",
         str(tmp_path / "probe")], input="int main(){return 0;}",
        capture_output=True, text=True, timeout=120)
    if probe.returncode != 0:
        pytest.skip("no ASan/UBSan runtime libraries")
    main_cpp = tmp_path / "main.cpp"
    main_cpp.write_text(_MAIN)
    exe = tmp_path / "san_harness"
    build = subprocess.run(
        ["g++", "-O1", "-g", "-std=c++17", "-fopenmp",
         "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
         str(SRC), str(main_cpp), "-o", str(exe)],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr
    run = subprocess.run([str(exe)], capture_output=True, text=True,
                         timeout=120,
                         env={**os.environ,
                              "ASAN_OPTIONS": "detect_leaks=1",
                              "UBSAN_OPTIONS": "print_stacktrace=1"})
    assert run.returncode == 0, run.stdout + run.stderr
    assert "sanitizer harness OK" in run.stdout
