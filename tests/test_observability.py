"""Fleet-wide request observability (docs/OBSERVABILITY.md "Serving
observability").

The contract under test:

  * trace context: header round-trip, head-sampling decision minted once
    and honored downstream, trace-id propagation END TO END through the
    fanout front onto a second replica after a transport failure — one
    trace, spans from two processes, merged onto one wall-clock-aligned
    timeline and time-ordered;
  * the tracer's wall-clock anchor (clock_sync in every export,
    re-anchored by reset()) and the one-shot event-drop warning +
    summary field;
  * ``/metrics`` output parses as VALID Prometheus text exposition
    (unique # TYPE per family, cumulative le buckets, _sum/_count) with
    counters monotone across scrapes, on replicas, the front, and the
    fleet aggregate (per-replica labels);
  * the SLO burn-rate monitor's state machine on an injected clock:
    healthy traffic -> no alert; burn -> fire (both windows); recovery
    -> clear on the fast window — for both the latency and availability
    dimensions;
  * tail capture of errored requests regardless of head sampling, and
    the JSONL access-log schema.
"""
import json
import os
import re
import signal
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.telemetry as tel
from lightgbm_tpu.serving import ServingApp, ServingFleet, SLOMonitor
from lightgbm_tpu.serving.front import http_json
from lightgbm_tpu.telemetry import TraceContext, TailRing
from lightgbm_tpu.telemetry.collect import merge_traces
from lightgbm_tpu.telemetry.prometheus import render_parts, render_prometheus


@pytest.fixture
def telemetry():
    tel.reset()
    tel.configure(enabled=True)
    yield tel
    tel.disable()
    tel.reset()
    tel.configure(enabled=False, metrics_out="", trace_out="")


def _make_data(seed=7, n=400):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    y = ((X[:, 1] > 0) ^ (X[:, 2] > 0.5)).astype(np.float64)
    return X, y


def _train_to_file(path, seed=3):
    X, y = _make_data()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5, "seed": seed},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    bst.save_model(str(path))
    return X


# ---------------------------------------------------------------------------
# trace context mechanics
# ---------------------------------------------------------------------------

def test_trace_header_roundtrip():
    ctx = TraceContext.mint(1.0)
    assert ctx.sampled and len(ctx.trace_id) == 16
    back = TraceContext.from_header(ctx.header_value())
    assert back.trace_id == ctx.trace_id and back.sampled
    assert TraceContext.from_header("abcd1234;s=0").sampled is False
    # garbage never crashes admission; it just mints a fresh context
    assert TraceContext.from_header(None) is None
    assert TraceContext.from_header("") is None
    assert TraceContext.from_header("no spaces allowed;s=1") is None
    assert TraceContext.from_header("x" * 100) is None


def test_head_sampling_rates():
    assert not TraceContext.mint(0.0).sampled
    assert all(TraceContext.mint(1.0).sampled for _ in range(20))


def test_tail_ring_bounded():
    ring = TailRing(4)
    for i in range(10):
        ring.add({"i": i})
    snap = ring.snapshot()
    assert snap["captured"] == 10 and len(snap["recent"]) == 4
    assert [r["i"] for r in snap["recent"]] == [6, 7, 8, 9]


# ---------------------------------------------------------------------------
# tracer satellites: wall-clock anchor, drop accounting
# ---------------------------------------------------------------------------

def test_export_carries_clock_sync_anchor(telemetry, tmp_path):
    t_before = time.time()
    tel.reset()                      # anchor taken here
    with tel.span("work"):
        pass
    path = tel.export_trace(str(tmp_path / "t.json"))
    blob = json.load(open(path))
    anchor = blob["otherData"]["clock_sync"]
    assert t_before <= anchor["unix_time_s"] <= time.time()
    assert anchor["pid"] == os.getpid()
    # the same anchor rides as a metadata event for tools that only see
    # traceEvents
    evs = [e for e in blob["traceEvents"] if e["name"] == "clock_sync"]
    assert evs and evs[0]["args"]["unix_time_s"] == anchor["unix_time_s"]
    # reset() re-anchors BOTH halves
    a1 = tel.global_tracer.clock_sync()
    time.sleep(0.01)
    tel.reset()
    a2 = tel.global_tracer.clock_sync()
    assert a2["unix_time_s"] > a1["unix_time_s"]
    assert a2["perf_epoch_s"] > a1["perf_epoch_s"]


def test_event_drop_warns_once_and_surfaces(telemetry, monkeypatch):
    from lightgbm_tpu.telemetry import tracer as tracer_mod
    from lightgbm_tpu.utils import log as logmod

    warnings = []
    monkeypatch.setattr(tracer_mod, "_MAX_EVENTS", 2)
    monkeypatch.setattr(logmod, "log_warning",
                        lambda msg: warnings.append(str(msg)))
    tel.reset()
    for _ in range(5):
        tel.instant("x")
    assert tel.global_tracer.dropped == 3
    assert tel.summary()["trace_dropped_events"] == 3
    dropped_warnings = [w for w in warnings if "DROPPED" in w]
    assert len(dropped_warnings) == 1      # one-shot, not per event


def test_complete_event_cross_thread(telemetry):
    t0 = time.perf_counter() - 0.25
    tel.global_tracer.complete("q", t0, 0.25, trace_id="ab")
    ev = [e for e in tel.global_tracer.events if e["name"] == "q"][0]
    assert ev["ph"] == "X"
    assert ev["dur"] == pytest.approx(0.25e6, rel=0.01)
    assert ev["args"]["trace_id"] == "ab"


def test_snapshot_exposes_histogram_buckets(telemetry):
    tel.observe("h", 0.002)
    tel.observe("h", 0.02)
    tel.observe("h", 999.0)
    h = tel.global_registry.snapshot()["histograms"]["h"]
    assert len(h["buckets"]) == len(h["bounds"]) + 1
    assert sum(h["buckets"]) == h["count"] == 3
    assert h["buckets"][-1] == 1      # the overflow bucket


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?[0-9.e+-]+|\+Inf)$")


def _parse_prom(text):
    """Minimal validity check of the 0.0.4 text format; returns
    {family: type} and {sample_line_name: value}."""
    types, samples = {}, {}
    for ln in text.strip().splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _, _, rest = ln.partition("# TYPE ")
            name, mtype = rest.rsplit(" ", 1)
            assert name not in types, f"duplicate # TYPE for {name}"
            types[name] = mtype
            continue
        assert not ln.startswith("#"), f"unexpected comment: {ln}"
        m = _SAMPLE.match(ln)
        assert m, f"invalid sample line: {ln!r}"
        samples[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    # every sample belongs to a declared family
    for key in samples:
        base = key.split("{")[0]
        fam = base
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[:-len(suffix)] in types:
                fam = base[:-len(suffix)]
        assert fam in types, f"sample {key} has no # TYPE"
    return types, samples


def test_prometheus_render_valid_and_cumulative(telemetry):
    tel.inc("serve/requests", 5)
    tel.gauge("fleet/replicas_alive", 3)
    tel.observe("serve/latency_s", 0.004)
    tel.observe("serve/latency_s", 0.5)
    text = tel.registry_text()
    types, samples = _parse_prom(text)
    assert types["lgbtpu_serve_requests_total"] == "counter"
    assert types["lgbtpu_fleet_replicas_alive"] == "gauge"
    assert types["lgbtpu_serve_latency_s"] == "histogram"
    assert samples["lgbtpu_serve_requests_total"] == 5
    # cumulative buckets: monotone nondecreasing, +Inf == _count
    buckets = [(k, v) for k, v in samples.items()
               if k.startswith("lgbtpu_serve_latency_s_bucket")]
    values = [v for _, v in buckets]
    assert values == sorted(values)
    assert samples['lgbtpu_serve_latency_s_bucket{le="+Inf"}'] == \
        samples["lgbtpu_serve_latency_s_count"] == 2


def test_prometheus_counters_monotone_across_scrapes(telemetry):
    tel.inc("serve/requests", 2)
    _, s1 = _parse_prom(tel.registry_text())
    tel.inc("serve/requests", 3)
    tel.observe("serve/latency_s", 0.01)
    _, s2 = _parse_prom(tel.registry_text())
    for key, v1 in s1.items():
        if "_total" in key or "_count" in key or "_bucket" in key:
            assert s2.get(key, v1) >= v1, f"{key} went backwards"


def test_prometheus_replica_relabeling(telemetry):
    tel.gauge("fleet/replica/3/up", 1.0)
    tel.gauge("fleet/replica/11/heartbeat_age_s", 0.25)
    types, samples = _parse_prom(tel.registry_text())
    assert samples['lgbtpu_fleet_replica_up{replica="3"}'] == 1.0
    assert samples[
        'lgbtpu_fleet_replica_heartbeat_age_s{replica="11"}'] == 0.25
    # the numeric rank lives in a label, never in the metric name
    assert not any("replica_3" in t or "replica_11" in t for t in types)


def test_prometheus_multi_part_single_type(telemetry):
    snap_a = {"counters": {"serve/requests": 4.0}, "gauges": {},
              "histograms": {}}
    snap_b = {"counters": {"serve/requests": 9.0}, "gauges": {},
              "histograms": {}}
    text = render_parts([({"role": "front"}, snap_a),
                         ({"role": "replica", "replica": "0"}, snap_b)])
    types, samples = _parse_prom(text)
    assert list(types) == ["lgbtpu_serve_requests_total"]
    assert samples['lgbtpu_serve_requests_total{role="front"}'] == 4.0
    assert samples['lgbtpu_serve_requests_total'
                   '{replica="0",role="replica"}'] == 9.0


def test_prometheus_multi_part_histogram_relabeling(telemetry):
    """Fleet aggregate (/metrics/fleet): per-replica histogram snapshots
    merge under exactly ONE ``# TYPE`` line, each replica keeping its own
    CUMULATIVE ``le`` ladder inside ``replica="<r>"`` label space."""
    def hist(buckets, total):
        return {"count": total, "sum_s": 0.5, "mean_s": 0.1, "min_s": 0.01,
                "max_s": 0.2, "bounds": [0.01, 0.1], "buckets": buckets}
    snap_a = {"counters": {}, "gauges": {},
              "histograms": {"serve/latency_s": hist([2, 1, 0], 3)}}
    snap_b = {"counters": {}, "gauges": {},
              "histograms": {"serve/latency_s": hist([1, 0, 4], 5)}}
    text = render_parts([({"role": "replica", "replica": "0"}, snap_a),
                         ({"role": "replica", "replica": "1"}, snap_b)])
    assert text.count("# TYPE lgbtpu_serve_latency_s histogram") == 1
    assert text.count("# TYPE") == 1
    types, samples = _parse_prom(text)
    # replica 0: cumulative 2 -> 3, +Inf == _count == 3
    assert samples['lgbtpu_serve_latency_s_bucket'
                   '{le="0.01",replica="0",role="replica"}'] == 2
    assert samples['lgbtpu_serve_latency_s_bucket'
                   '{le="0.1",replica="0",role="replica"}'] == 3
    assert samples['lgbtpu_serve_latency_s_bucket'
                   '{le="+Inf",replica="0",role="replica"}'] == \
        samples['lgbtpu_serve_latency_s_count'
                '{replica="0",role="replica"}'] == 3
    # replica 1: its own independent ladder, 1 -> 1, +Inf == 5
    assert samples['lgbtpu_serve_latency_s_bucket'
                   '{le="0.01",replica="1",role="replica"}'] == 1
    assert samples['lgbtpu_serve_latency_s_bucket'
                   '{le="0.1",replica="1",role="replica"}'] == 1
    assert samples['lgbtpu_serve_latency_s_bucket'
                   '{le="+Inf",replica="1",role="replica"}'] == 5
    # a fleet/replica/<r>/-named histogram relabels the same way
    snap_c = {"counters": {}, "gauges": {},
              "histograms": {"fleet/replica/7/lat_s": hist([1, 1, 0], 2)}}
    types, samples = _parse_prom(render_parts([({}, snap_c)]))
    assert types == {"lgbtpu_fleet_replica_lat_s": "histogram"}
    assert samples['lgbtpu_fleet_replica_lat_s_bucket'
                   '{le="0.1",replica="7"}'] == 2


def test_prometheus_handles_legacy_snapshot_without_buckets():
    # a pre-anchor snapshot (no bounds/buckets) must not crash the
    # exporter — the histogram is simply omitted
    snap = {"counters": {}, "gauges": {},
            "histograms": {"h": {"count": 2, "sum_s": 0.1, "mean_s": 0.05,
                                 "min_s": 0.01, "max_s": 0.09}}}
    assert render_prometheus(snap) == ""


# ---------------------------------------------------------------------------
# SLO burn-rate monitor on an injected clock
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_slo_latency_burn_fire_and_clear():
    clk = _Clock()
    mon = SLOMonitor(p99_target_ms=100.0, window_s=5.0,
                     burn_threshold=10.0, clock=clk, min_events=5)
    # healthy: 1s of fast traffic
    for _ in range(50):
        mon.record(200, 20.0)
    assert mon.tick()["alert"] is None
    # burn: 3s where half the responses blow the p99 target (burn = 50x)
    for _ in range(3):
        clk.t += 1.0
        for _ in range(20):
            mon.record(200, 20.0)
            mon.record(200, 500.0)
    out = mon.tick()
    assert out["alert"] == "latency"
    assert mon.state()["alerting"]
    assert [e["kind"] for e in mon.timeline()] == ["fire"]
    # recovery: the fast window (5s) outruns the incident
    for _ in range(7):
        clk.t += 1.0
        for _ in range(30):
            mon.record(200, 20.0)
        mon.tick()
    assert mon.state()["alerting"] is False
    kinds = [e["kind"] for e in mon.timeline()]
    assert kinds == ["fire", "clear"]
    ts = [e["t"] for e in mon.timeline()]
    assert ts == sorted(ts)


def test_slo_availability_dimension_and_503_exemption():
    clk = _Clock()
    mon = SLOMonitor(availability_target=0.99, window_s=5.0,
                     burn_threshold=5.0, clock=clk, min_events=5)
    # 503 sheds are NOT availability errors (load management)
    for _ in range(100):
        mon.record(503, 5.0)
    assert mon.tick()["alert"] is None
    # non-503 5xx errors burn the budget
    for _ in range(3):
        clk.t += 1.0
        for _ in range(10):
            mon.record(200, 5.0)
            mon.record(500, 5.0)
    assert mon.tick()["alert"] == "availability"
    # idle recovery: the poll-loop tick clears once the window drains
    clk.t += 10.0
    mon.tick()
    assert mon.state()["alerting"] is False


def test_slo_min_events_guard():
    clk = _Clock()
    mon = SLOMonitor(p99_target_ms=10.0, window_s=5.0,
                     burn_threshold=2.0, clock=clk, min_events=10)
    # 3 catastrophic requests are not statistically an outage
    for _ in range(3):
        mon.record(200, 500.0)
    assert mon.tick()["alert"] is None


def test_slo_rejects_bad_target():
    with pytest.raises(ValueError):
        SLOMonitor(availability_target=1.5)


def test_outcome_helper_slo_status_override_and_schema():
    """The shared outcome recorder: the front maps transport-exhausted
    sheds to 599 against the SLO (availability burns during a total
    outage) while the record keeps the client-visible 503."""
    from lightgbm_tpu.telemetry.context import note_outcome

    clk = _Clock()
    mon = SLOMonitor(availability_target=0.99, window_s=5.0,
                     burn_threshold=1.0, clock=clk, min_events=5)
    ring = TailRing(8)
    ctx = TraceContext.mint(0.0)
    for _ in range(10):
        note_outcome(ctx=ctx, status=503, latency_ms=12.0,
                     deadline_ms=100.0,
                     obj={"reason": "retries_exhausted"},
                     slo=mon, tail=ring, retries=2, slo_status=599)
    assert mon.tick()["alert"] == "availability"
    rec = ring.snapshot()["recent"][-1]
    assert rec["outcome"] == 503          # the client saw an honest 503
    assert rec["retries"] == 2 and rec["captured"] == "error"
    assert rec["reason"] == "retries_exhausted"


def test_replica_slo_alert_clears_while_idle(tmp_path, telemetry):
    """The replica's own ticker thread must CLEAR an alert with zero
    traffic — the front stops routing to a burning replica, so waiting
    for the next request to tick would latch the alert forever."""
    model = tmp_path / "m.txt"
    _train_to_file(model)
    app = ServingApp(str(model), port=0, max_delay_ms=1.0,
                     slo_availability=0.99, slo_window_s=1.0).start()
    try:
        for _ in range(30):
            app.slo.record(500, 5.0)
        app.slo.tick()
        assert app.slo.state()["alerting"]
        deadline = time.time() + 8
        while app.slo.state()["alerting"] and time.time() < deadline:
            time.sleep(0.2)      # only the ticker thread can clear it
        assert not app.slo.state()["alerting"]
        assert app.slo.cleared == 1
    finally:
        app.shutdown()


# ---------------------------------------------------------------------------
# collector: merge + align + filter
# ---------------------------------------------------------------------------

def _shard(path, unix0, pid, events):
    blob = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": f"proc-{pid}"}}] + events,
        "otherData": {"clock_sync": {"unix_time_s": unix0,
                                     "perf_epoch_s": 0.0, "pid": pid}}}
    path.write_text(json.dumps(blob))
    return str(path)


def test_collector_aligns_shards_on_wall_clock(tmp_path):
    # shard B's epoch is 2s later than A's: its local ts 0 must land at
    # +2s on the merged timeline
    a = _shard(tmp_path / "trace_a.json", 100.0, 11, [
        {"name": "front/request", "ph": "B", "pid": 11, "tid": 1,
         "ts": 0.0, "args": {"trace_id": "t1"}},
        {"name": "front/request", "ph": "E", "pid": 11, "tid": 1,
         "ts": 3_000_000.0},
    ])
    b = _shard(tmp_path / "trace_b.json", 102.0, 22, [
        {"name": "serve/predict", "ph": "B", "pid": 22, "tid": 1,
         "ts": 0.0, "args": {"trace_id": "t1"}},
        {"name": "serve/predict", "ph": "E", "pid": 22, "tid": 1,
         "ts": 500_000.0},
    ])
    blob, summary = merge_traces([a, b])
    assert summary["shards"] == 2 and summary["unaligned_shards"] == []
    evs = [e for e in blob["traceEvents"] if e.get("ph") != "M"]
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    by_name = {e["name"]: e["ts"] for e in evs if e["ph"] == "B"}
    assert by_name["serve/predict"] == pytest.approx(2_000_000.0)
    assert by_name["front/request"] == pytest.approx(0.0)
    assert sorted(summary["processes"]) == [11, 22]


def test_collector_trace_id_filter_and_batch_membership(tmp_path):
    a = _shard(tmp_path / "trace_a.json", 50.0, 5, [
        {"name": "serve/dispatch", "ph": "B", "pid": 5, "tid": 1,
         "ts": 10.0, "args": {"trace_ids": ["want", "other"]}},
        {"name": "serve/predict", "ph": "B", "pid": 5, "tid": 1,
         "ts": 5.0, "args": {"trace_id": "unrelated"}},
    ])
    blob, summary = merge_traces([a], trace_id="want")
    names = [e["name"] for e in blob["traceEvents"] if e.get("ph") != "M"]
    assert names == ["serve/dispatch"]     # list membership matched


def test_collector_unaligned_shard_flagged(tmp_path):
    p = tmp_path / "trace_old.json"
    p.write_text(json.dumps({"traceEvents": [
        {"name": "x", "ph": "i", "pid": 1, "tid": 1, "ts": 1.0}]}))
    blob, summary = merge_traces([str(p)])
    assert summary["unaligned_shards"] == [str(p)]


# ---------------------------------------------------------------------------
# replica server surfaces (in-process)
# ---------------------------------------------------------------------------

@pytest.fixture
def app(tmp_path_factory, telemetry):
    td = tmp_path_factory.mktemp("obs_app")
    model = td / "model.txt"
    X = _train_to_file(model)
    access = str(td / "access.jsonl")
    app = ServingApp(str(model), port=0, max_delay_ms=1.0,
                     trace_sample=1.0, access_log=access,
                     slo_p99_ms=60_000.0).start()
    yield app, X, access
    app.shutdown()


def test_server_trace_metrics_access_log_and_tail(app):
    app, X, access = app
    # 1) normal predict: trace id minted, echoed in body AND header
    st, obj, hdrs = http_json(app.host, app.port, "POST", "/predict",
                              {"rows": X[:4].tolist()}, timeout=10)
    assert st == 200 and re.fullmatch(r"[0-9a-f]{16}", obj["trace_id"])
    echoed = {k.lower(): v for k, v in hdrs.items()}["x-lgbtpu-trace"]
    assert echoed.startswith(obj["trace_id"])
    # 2) propagated context wins over minting
    st, obj2, _ = http_json(
        app.host, app.port, "POST", "/predict",
        {"rows": X[:2].tolist()}, timeout=10,
        headers={"X-LGBTPU-Trace": "feedface00000001;s=1"})
    assert st == 200 and obj2["trace_id"] == "feedface00000001"
    spans = {e["name"]: e for e in tel.global_tracer.events
             if e.get("args", {}).get("trace_id") == "feedface00000001"}
    assert "serve/predict" in spans       # replica span carries the id
    assert "serve/queue_wait" in spans    # batcher queue wait rode along
    # 3) a shape error is tail-captured even though it was head-sampled
    #    anyway; the ring keeps it as an error
    st, obj3, _ = http_json(app.host, app.port, "POST", "/predict",
                            {"rows": [[1.0, 2.0]]}, timeout=10)
    assert st == 400
    st, stats, _ = http_json(app.host, app.port, "GET", "/stats",
                             timeout=10)
    tail = stats["trace_tail"]
    assert tail["captured"] >= 1
    assert any(r["outcome"] == 400 for r in tail["recent"])
    assert stats["slo"]["alerting"] is False
    # 4) /metrics is valid exposition and counts the traffic
    st, snap, _ = http_json(app.host, app.port, "GET",
                            "/metrics?format=json", timeout=10)
    assert st == 200 and snap["counters"]["serve/requests"] >= 2
    import urllib.request
    text = urllib.request.urlopen(
        f"http://{app.host}:{app.port}/metrics", timeout=10
    ).read().decode()
    types, samples = _parse_prom(text)
    assert samples["lgbtpu_serve_requests_total"] >= 2
    # 5) the access log has one line per finished request, schema intact
    lines = [json.loads(ln) for ln in open(access)]
    assert len(lines) == 3
    assert {ln["outcome"] for ln in lines} == {200, 400}
    for ln in lines:
        for key in ("ts", "trace_id", "outcome", "latency_ms",
                    "deadline_ms", "retries", "model_sha256"):
            assert key in ln, f"access log missing {key}"
    ok = [ln for ln in lines if ln["outcome"] == 200]
    assert all(ln["model_sha256"] for ln in ok)


def test_server_unsampled_requests_emit_no_spans(tmp_path, telemetry):
    model = tmp_path / "m.txt"
    X = _train_to_file(model)
    app = ServingApp(str(model), port=0, max_delay_ms=1.0,
                     trace_sample=0.0).start()
    try:
        tel.global_tracer.reset()
        st, obj, _ = http_json(app.host, app.port, "POST", "/predict",
                               {"rows": X[:2].tolist()}, timeout=10)
        assert st == 200 and "trace_id" in obj   # id still minted
        names = {e["name"] for e in tel.global_tracer.events}
        assert "serve/predict" not in names
        assert "serve/queue_wait" not in names
    finally:
        app.shutdown()


# ---------------------------------------------------------------------------
# the real fleet: one trace across two processes + /metrics everywhere
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_trace_propagation_metrics_and_merge(tmp_path, telemetry):
    """End to end: a request that fails over from a killed replica onto
    its sibling carries ONE trace id through the front's retry; the
    merged shards show the request on one timeline with spans from two
    processes; /metrics is valid on the front, a replica, and the fleet
    aggregate."""
    model = tmp_path / "model.txt"
    X = _train_to_file(model)
    fleet_dir = str(tmp_path / "fleet")
    fleet = ServingFleet(
        str(model), replicas=2, max_batch=16, buckets_spec="16",
        max_delay_ms=1.0, deadline_ms=5000.0, retries=2,
        retry_backoff_ms=5.0, breaker_failures=3, breaker_cooldown_s=0.5,
        restart_backoff_s=8.0,      # slow enough that the killed replica
        #                             stays down for the whole test
        hang_timeout_s=10.0, fleet_dir=fleet_dir,
        trace_sample=1.0, access_log=str(tmp_path / "access")).start()
    try:
        # warm both replicas through the front
        for _ in range(4):
            st, obj, _ = http_json(fleet.host, fleet.port, "POST",
                                   "/predict",
                                   {"rows": X[:3].tolist(),
                                    "deadline_ms": 4000}, timeout=30)
            assert st == 200, obj

        # ---- /metrics: replica, front, fleet aggregate all valid
        ep1 = fleet.endpoint(1)
        import urllib.request
        rep_text = urllib.request.urlopen(
            f"http://{ep1['host']}:{ep1['port']}/metrics",
            timeout=10).read().decode()
        types, samples = _parse_prom(rep_text)
        assert any(k.startswith('lgbtpu_serve_requests_total')
                   for k in samples)
        front_text = urllib.request.urlopen(
            f"http://{fleet.host}:{fleet.port}/metrics",
            timeout=10).read().decode()
        _parse_prom(front_text)
        assert "lgbtpu_fleet_replicas_ready" in front_text
        agg_text = urllib.request.urlopen(
            f"http://{fleet.host}:{fleet.port}/metrics/fleet",
            timeout=10).read().decode()
        _parse_prom(agg_text)
        assert 'role="front"' in agg_text
        assert 'replica="0"' in agg_text and 'replica="1"' in agg_text

        # ---- wedge replica 0 (SIGSTOP: its socket stays open, requests
        # time out — exactly what a stuck XLA dispatch looks like), then
        # push traced requests until one fails over onto the sibling.
        # Deterministic: until the readiness cache notices (~1.5 s) the
        # round-robin keeps routing there, so a retry MUST happen.
        stopped_pid = fleet.endpoint(0)["pid"]
        os.kill(stopped_pid, signal.SIGSTOP)
        traced = None
        deadline = time.time() + 30
        n = 0
        try:
            while time.time() < deadline:
                n += 1
                tid = f"{n:016x}"
                st, obj, _ = http_json(
                    fleet.host, fleet.port, "POST", "/predict",
                    {"rows": X[:2].tolist(), "deadline_ms": 4000},
                    timeout=30,
                    headers={"X-LGBTPU-Trace": f"{tid};s=1"})
                if st == 200 and obj.get("attempts", 1) >= 2:
                    assert obj["trace_id"] == tid
                    traced = tid
                    break
                time.sleep(0.02)
        finally:
            os.kill(stopped_pid, signal.SIGCONT)   # let it drain+export
        assert traced, "no request ever needed a retry onto the sibling"
    finally:
        fleet.stop()

    # ---- replicas exported shards on drain, the front on stop; merge
    shards = sorted(os.listdir(fleet_dir))
    assert "trace_front.json" in shards
    assert any(s.startswith("trace_replica_") for s in shards)
    paths = [os.path.join(fleet_dir, s) for s in shards
             if s.startswith("trace")]
    blob, summary = merge_traces(paths, trace_id=traced)
    evs = [e for e in blob["traceEvents"] if e.get("ph") != "M"]
    assert evs, "merged trace lost the request"
    # one trace, spans from TWO processes (front + surviving replica)
    pids = {e["pid"] for e in evs}
    assert len(pids) >= 2, f"expected >= 2 processes, got {pids}"
    names = {e["name"] for e in evs}
    assert "front/request" in names        # front process
    assert "front/retry" in names          # the failover is on the trace
    assert "serve/predict" in names        # replica process
    assert "serve/queue_wait" in names     # batcher
    assert "serve/dispatch" in names       # device dispatch
    # time-ordered on the merged wall-clock timeline
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    # the front's request span opens before the replica's handler span
    first_front = min(e["ts"] for e in evs
                      if e["name"] == "front/request")
    first_replica = min(e["ts"] for e in evs
                        if e["name"] == "serve/predict")
    assert first_front <= first_replica
    # access logs: front log stamps the retry count
    front_log = os.path.join(str(tmp_path / "access"),
                             "access_front.jsonl")
    entries = [json.loads(ln) for ln in open(front_log)]
    hit = [e for e in entries if e["trace_id"] == traced]
    assert hit and hit[0]["retries"] >= 1 and hit[0]["outcome"] == 200
