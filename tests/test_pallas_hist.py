"""Pallas histogram kernel correctness (interpret mode on CPU) vs the segsum oracle.

Reference analog of what is being validated: dense_bin.hpp ConstructHistogramInner
semantics — per-slot (grad, hess, count) sums over bins, with invalid rows skipped."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import _hist_segsum, build_histograms
from lightgbm_tpu.pallas import hist_kernel as hk


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = hk._INTERPRET
    hk._INTERPRET = True
    yield
    hk._INTERPRET = old


def _mk(n, g, s, b, seed=0, frac_invalid=0.3):
    rs = np.random.RandomState(seed)
    bins = jnp.asarray(rs.randint(0, b, size=(n, g)), jnp.uint8)
    slot = rs.randint(0, s, size=n)
    slot[rs.rand(n) < frac_invalid] = -1
    slot = jnp.asarray(slot, jnp.int32)
    grad = jnp.asarray(rs.randn(n), jnp.float32)
    hess = jnp.asarray(rs.rand(n), jnp.float32)
    cnt = jnp.asarray((rs.rand(n) > 0.2), jnp.float32)
    return bins, slot, grad, hess, cnt


@pytest.mark.parametrize("bmax", [64, 100, 128])
def test_direct_kernel_matches_segsum(bmax):
    n, g, s = 3000, 5, 4
    bins, slot, grad, hess, cnt = _mk(n, g, s, bmax)
    ref = _hist_segsum(bins, slot, grad, hess, cnt, s, bmax)
    got = hk.build_histograms_sorted(bins, slot, grad, hess, cnt, s, bmax,
                                     block_rows=512)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4)


@pytest.mark.parametrize("bmax", [200, 256])
def test_nibble_kernel_matches_segsum(bmax):
    n, g, s = 3000, 3, 4
    bins, slot, grad, hess, cnt = _mk(n, g, s, bmax)
    ref = _hist_segsum(bins, slot, grad, hess, cnt, s, bmax)
    got = hk.build_histograms_sorted(bins, slot, grad, hess, cnt, s, bmax,
                                     block_rows=512)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4)


def test_single_slot_root_plan():
    n, g, bmax = 2500, 4, 64
    bins, _, grad, hess, cnt = _mk(n, g, 1, bmax)
    slot = jnp.zeros(n, jnp.int32)
    ref = _hist_segsum(bins, slot, grad, hess, cnt, 1, bmax)
    got = hk.build_histograms_sorted(bins, slot, grad, hess, cnt, 1, bmax,
                                     block_rows=512)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4)


def test_empty_slots_are_zero():
    n, g, s, bmax = 1000, 3, 6, 64
    bins, _, grad, hess, cnt = _mk(n, g, s, bmax)
    # only slots 1 and 4 populated
    rs = np.random.RandomState(3)
    slot = jnp.asarray(rs.choice([-1, 1, 4], size=n), jnp.int32)
    got = hk.build_histograms_sorted(bins, slot, grad, hess, cnt, s, bmax,
                                     block_rows=256)
    got = np.asarray(got)
    for empty in (0, 2, 3, 5):
        assert np.all(got[empty] == 0.0)
    ref = _hist_segsum(bins, slot, grad, hess, cnt, s, bmax)
    np.testing.assert_allclose(got, np.asarray(ref), atol=1e-4)


def test_pallas_backend_reachable_via_build_histograms():
    n, g, s, bmax = 1200, 4, 3, 64
    bins, slot, grad, hess, cnt = _mk(n, g, s, bmax)
    ref = build_histograms(bins, slot, grad, hess, cnt, s, bmax, backend="segsum")
    got = build_histograms(bins, slot, grad, hess, cnt, s, bmax, backend="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4)
