"""Closed-loop freshness (docs/ROBUSTNESS.md "Closed-loop freshness").

The contract under test:

  * the device leaf-value refit (stream-kernel route replay + f64 segment
    sums) is BITWISE equal to the host NumPy ``FitByExistingTree``
    reference — weighted sums and ``refit_decay_rate`` included — and the
    leaf-assignment pass reuses the stream kernel (telemetry counter, no
    new O(N*depth) host walk);
  * refit on fresh data streamed through the ingest pipeline is
    byte-identical to the in-memory arm (LGBTPU_INGEST A/B);
  * checkpoint/resume stays bit-identical THROUGH a refit step;
  * ``task=pipeline`` closes the loop end to end: train -> refit ->
    validation gate -> atomic pointer promotion, and every chaos fault
    (poisoned refit, torn pointer) leaves the fleet pointer untouched.
"""
import copy
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.model_io import refit_model
from lightgbm_tpu.refit import refit_leaf_values
from lightgbm_tpu.serving.fleet import generation_history, read_pointer

from conftest import make_synthetic_regression

PARAMS = {"objective": "regression", "num_leaves": 15, "min_data_in_leaf": 5,
          "verbosity": -1, "seed": 7}


def _fresh_split(n=1200, f=8, seed=0):
    X, y = make_synthetic_regression(n=2 * n, f=f, seed=seed)
    return X[:n], y[:n], X[n:], y[n:]


def _leaf_values(bst):
    return [np.asarray(t.leaf_value, np.float64) for t in bst._all_trees()]


# ---------------------------------------------------------------------------
# device refit == host reference, bitwise
# ---------------------------------------------------------------------------

def test_device_refit_bitwise_vs_host_reference():
    X, y, X2, y2 = _fresh_split()
    X = X.copy()
    X[::17, 3] = np.nan                       # default-direction routing
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=8)

    ref = refit_model(bst, X2, y2, decay_rate=0.9)

    cand = lgb.Booster(model_str=bst.model_to_string())
    ds2 = lgb.Dataset(X2, label=y2, reference=ds)
    telemetry.configure(enabled=True)
    telemetry.reset()
    try:
        before = telemetry.global_registry.snapshot()["counters"].get(
            "refit/route_replay_passes", 0)
        report = refit_leaf_values(cand, ds2, decay_rate=0.9)
        counters = telemetry.global_registry.snapshot()["counters"]
    finally:
        telemetry.configure(enabled=False)

    # every tree went through the stream kernel's route-only replay — the
    # acceptance criterion that no new O(N*depth) host walk was added
    assert report["route_replay_passes"] == report["trees"] == 8
    assert report["walk_fallback_passes"] == 0
    assert counters.get("refit/route_replay_passes", 0) - before == 8

    for i, (a, b) in enumerate(zip(_leaf_values(cand), _leaf_values(ref))):
        np.testing.assert_array_equal(
            a, b, err_msg=f"tree {i} leaf values diverge from host refit")


def test_device_refit_weighted_decay_analytic():
    """Single-tree model, L2 objective, sample weights: the refit value
    has a closed form — new = sum(w*(y-score)) / (sum(w)+l2+eps) *
    shrinkage, blended by decay — computable with np.bincount alone
    (no shared code with the implementation under test)."""
    X, y, X2, y2 = _fresh_split(n=800)
    rs = np.random.RandomState(3)
    w2 = rs.uniform(0.5, 2.0, size=y2.shape[0])
    p = dict(PARAMS, boost_from_average=False, lambda_l2=0.7)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(p, ds, num_boost_round=1)
    (tree,) = bst._all_trees()
    old = np.asarray(tree.leaf_value, np.float64).copy()
    leaf = tree.predict_leaf_raw(np.asarray(X2, np.float64))

    # gradients exactly as RegressionL2 computes them: f32 elementwise
    y32, w32 = np.float32(y2), np.float32(w2)
    g = (np.float32(0.0) - y32) * w32          # score starts at zero
    h = np.ones_like(y32) * w32
    sum_g = np.bincount(leaf, weights=np.float64(g),
                        minlength=tree.num_leaves)
    sum_h = np.bincount(leaf, weights=np.float64(h),
                        minlength=tree.num_leaves)
    new = -sum_g / (sum_h + 0.7 + 1e-15) * tree.shrinkage
    has = np.bincount(leaf, minlength=tree.num_leaves) > 0
    want = np.where(has, 0.6 * old + 0.4 * new, old)

    # refit the engine booster itself so its configured lambda_l2 applies
    # (a string-loaded booster carries no config, like the host reference)
    ds2 = lgb.Dataset(X2, label=y2, weight=w2, reference=ds)
    refit_leaf_values(bst, ds2, decay_rate=0.6)
    np.testing.assert_array_equal(_leaf_values(bst)[0], want)


# ---------------------------------------------------------------------------
# streamed fresh data + checkpoint interplay
# ---------------------------------------------------------------------------

def _refit_arm(base_csv, fresh_csv, mode, params):
    os.environ["LGBTPU_INGEST"] = mode
    if mode == "stream":
        os.environ["LGBTPU_INGEST_CHUNK"] = "300"
    try:
        ds = lgb.Dataset(base_csv, params=dict(params))
        bst = lgb.train(dict(params), ds, num_boost_round=6)
        ds2 = lgb.Dataset(fresh_csv, params=dict(params), reference=ds)
        refit_leaf_values(bst, ds2, decay_rate=0.85)
        return bst.model_to_string(), getattr(ds2, "ingest_stats", None)
    finally:
        os.environ.pop("LGBTPU_INGEST", None)
        os.environ.pop("LGBTPU_INGEST_CHUNK", None)


def test_refit_streamed_appended_data_byte_identical(tmp_path):
    """PR 14 interplay: fresh data streamed chunk-by-chunk through the
    ingest pipeline must refit to the byte-identical model."""
    X, y, X2, y2 = _fresh_split(n=1000, f=6)
    base, fresh = str(tmp_path / "base.csv"), str(tmp_path / "fresh.csv")
    np.savetxt(base, np.column_stack([y, X]), delimiter=",", fmt="%.9g")
    np.savetxt(fresh, np.column_stack([y2, X2]), delimiter=",", fmt="%.9g")
    m_in, _ = _refit_arm(base, fresh, "inmem", PARAMS)
    m_st, stats = _refit_arm(base, fresh, "stream", PARAMS)
    assert stats and stats.get("mode") == "stream"
    assert m_st == m_in


def test_checkpoint_resume_bit_identity_through_refit(tmp_path):
    """PR 3 interplay: resume from a mid-training snapshot, then refit —
    the result must be byte-identical to the uninterrupted run's refit."""
    X, y, X2, y2 = _fresh_split()
    M = tmp_path / "model.txt"
    p = dict(PARAMS, snapshot_freq=4, output_model=str(M))
    ds = lgb.Dataset(X, label=y)
    full = lgb.train(p, ds, num_boost_round=8)
    resumed = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=8,
                        resume_from=str(M) + ".snapshot_iter_4")
    assert resumed.model_to_string() == full.model_to_string()
    ds2 = lgb.Dataset(X2, label=y2, reference=ds)
    refit_leaf_values(full, ds2, decay_rate=0.9)
    refit_leaf_values(resumed, ds2, decay_rate=0.9)
    assert resumed.model_to_string() == full.model_to_string()


# ---------------------------------------------------------------------------
# the closed loop end to end (pointer-only fleet: no replica processes)
# ---------------------------------------------------------------------------

def _make_csvs(tmp_path, seed=11):
    X, y, X2, y2 = _fresh_split(n=500, f=5, seed=seed)
    paths = {}
    for name, (Xa, ya) in (("base", (X, y)), ("fresh", (X2, y2)),
                           ("hold", (X2[:150], y2[:150]))):
        paths[name] = str(tmp_path / f"{name}.csv")
        np.savetxt(paths[name], np.column_stack([ya, Xa]), delimiter=",",
                   fmt="%.7g")
    return paths


def _pipeline_args(paths, out, fleet_dir, **extra):
    args = ["task=pipeline", f"pipeline_fresh_data={paths['fresh']}",
            f"valid={paths['hold']}", f"output_model={out}",
            "objective=regression", "num_iterations=6", "num_leaves=15",
            "min_data_in_leaf=5", "pipeline_refit_iterations=1",
            "pipeline_gate_margin=0.1",    # chaos arms test faults, not fit
            "verbosity=-1", "seed=7", f"serve_fleet_dir={fleet_dir}"]
    args += [f"{k}={v}" for k, v in extra.items()]
    return args


def test_pipeline_end_to_end_and_chaos_gate(tmp_path):
    from lightgbm_tpu import cli

    paths = _make_csvs(tmp_path)
    out = str(tmp_path / "model.txt")
    fd = str(tmp_path / "fleet")
    os.makedirs(fd)

    # clean pass: one CLI invocation runs train -> refit -> gate ->
    # promote; the pointer lands on generation 1
    rc = cli.main(_pipeline_args(paths, out, fd, data=paths["base"],
                                 snapshot_freq=3))
    assert rc == 0
    p1 = read_pointer(fd)
    assert p1 and p1["generation"] == 1
    # candidate paths are generation-unique so later runs cannot clobber
    # the file the pointer serves
    assert p1["path"] == out + ".candidate_gen1"
    assert os.path.exists(p1["path"])
    assert os.path.exists(p1["path"] + ".quality.json")      # PR 16 gate

    # poisoned refit: nan_guard fails the gate; pointer byte-untouched
    os.environ["LGBTPU_CHAOS"] = "poison_refit:count=4"
    try:
        rc2 = cli.main(_pipeline_args(paths, out, fd,
                                      input_model=out))
    finally:
        os.environ.pop("LGBTPU_CHAOS", None)
    assert rc2 == 1
    assert read_pointer(fd) == p1

    # torn pointer write: promotion reports failure; history still
    # carries the generation counter, so the next clean run recovers
    marker = str(tmp_path / "torn.marker")
    os.environ["LGBTPU_CHAOS"] = f"torn_pointer:once={marker}"
    try:
        rc3 = cli.main(_pipeline_args(paths, out, fd, input_model=out))
    finally:
        os.environ.pop("LGBTPU_CHAOS", None)
    assert rc3 == 1
    rc4 = cli.main(_pipeline_args(paths, out, fd, input_model=out))
    assert rc4 == 0
    p4 = read_pointer(fd)
    assert p4["generation"] == 3           # 1 (clean) + torn 2 + clean 3
    gens = [h["generation"] for h in generation_history(fd)]
    assert gens == [1, 2, 3]


def test_pipeline_gate_margin_blocks_regression(tmp_path):
    """A candidate that regresses the holdout metric beyond the margin
    must not touch the pointer (rc 1, gate failure recorded)."""
    from lightgbm_tpu.pipeline import run_pipeline

    paths = _make_csvs(tmp_path, seed=5)
    out = str(tmp_path / "model.txt")
    fd = str(tmp_path / "fleet")
    os.makedirs(fd)
    base_params = {"task": "pipeline", "data": paths["base"],
                   "pipeline_fresh_data": paths["fresh"],
                   "valid": paths["hold"], "output_model": out,
                   "objective": "regression", "num_iterations": 6,
                   "num_leaves": 15, "min_data_in_leaf": 5,
                   "pipeline_refit_iterations": 1, "verbosity": -1,
                   "seed": 7, "serve_fleet_dir": fd}
    rep = run_pipeline(dict(base_params))
    assert rep["ok"] and read_pointer(fd)["generation"] == 1
    # an impossible margin on an equal-or-better candidate still passes;
    # flip the comparison by demanding the candidate beat the baseline by
    # a margin no refit can deliver on identical data
    worse = dict(base_params, input_model=out,
                 pipeline_refit_iterations=0, refit_decay_rate=1.0,
                 pipeline_gate_margin=-1e6)
    rep2 = run_pipeline(worse)
    assert not rep2["ok"]
    assert "FAIL" in rep2["gate"]["checks"]["holdout_metric"]
    assert read_pointer(fd)["generation"] == 1
