"""Single-row fast predict path (reference: c_api.h:1399-1428
PredictForMatSingleRowFastInit/Fast).  Correctness vs the batch predictor
and a latency pin proving no device dispatch happens per call."""
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _fit_model(objective="binary", n=800, num_class=1, cat=True):
    rs = np.random.RandomState(7)
    X = rs.randn(n, 6)
    if cat:
        X[:, 4] = rs.randint(0, 9, n)
    X[rs.rand(n) < 0.15, 0] = np.nan
    if objective == "multiclass":
        y = rs.randint(0, num_class, n).astype(np.float64)
        y[X[:, 1] > 0.5] = 0
    else:
        y = ((X[:, 1] > 0) ^ (X[:, 4] == 3)).astype(np.float64)
    params = {"objective": objective, "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "seed": 3}
    if objective == "multiclass":
        params["num_class"] = num_class
    ds = lgb.Dataset(X, label=y,
                     categorical_feature=[4] if cat else "auto")
    return lgb.train(params, ds, num_boost_round=5), X


@pytest.mark.parametrize("raw", [True, False])
def test_single_row_matches_batch(raw):
    bst, X = _fit_model()
    batch = bst.predict(X[:50], raw_score=raw)
    fast = bst.predict_single_row_fast_init(raw_score=raw)
    got = np.array([fast(X[i]) for i in range(50)])
    # raw scores are bit-exact; probabilities differ ~1e-7 (the engine
    # sigmoid is float32-jax, the serving transform float64-numpy)
    tol = 1e-12 if raw else 1e-6
    np.testing.assert_allclose(got, batch, rtol=tol, atol=tol)


def test_predict_one_row_uses_fast_path_and_matches():
    bst, X = _fit_model()
    batch = bst.predict(X[:20], raw_score=True)
    one_by_one = np.concatenate(
        [bst.predict(X[i:i + 1], raw_score=True) for i in range(20)])
    np.testing.assert_allclose(one_by_one, batch, rtol=1e-12, atol=1e-12)
    assert getattr(bst, "_fast1_cache", None) is not None


def test_single_row_multiclass():
    bst, X = _fit_model(objective="multiclass", num_class=3, cat=False)
    batch = bst.predict(X[:25])
    fast = bst.predict_single_row_fast_init()
    got = np.stack([fast(X[i]) for i in range(25)])
    np.testing.assert_allclose(got, batch, rtol=1e-6, atol=1e-7)


def test_single_row_model_roundtrip_and_nan():
    bst, X = _fit_model()
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    fast = bst2.predict_single_row_fast_init(raw_score=True)
    row = X[3].copy()
    row[0] = np.nan
    np.testing.assert_allclose(
        fast(row), bst.predict(row.reshape(1, -1), raw_score=True)[0],
        rtol=1e-12)


def test_single_row_wrong_feature_count():
    bst, X = _fit_model()
    fast = bst.predict_single_row_fast_init()
    with pytest.raises(lgb.LightGBMError, match="6"):
        fast(X[0, :4])


def test_raw_predict_validates_row_length():
    """raw_predict is the serving hot path: a short row must raise, not
    let the native walk read past the buffer."""
    bst, X = _fit_model()
    fast = bst.predict_single_row_fast_init(raw_score=True)
    with pytest.raises(lgb.LightGBMError, match="expects 6 features"):
        fast.raw_predict(X[0, :5])
    with pytest.raises(lgb.LightGBMError, match="got 8"):
        fast.raw_predict(np.zeros(8))


def test_prebind_iteration_slicing():
    """SingleRowFastPredictor honors start_iteration/num_iteration at
    pre-bind time (the FastConfig carries the iteration window)."""
    from lightgbm_tpu.predict_fast import SingleRowFastPredictor

    bst, X = _fit_model()
    trees = bst._all_trees()
    for start, num in ((0, 2), (1, 2), (2, None), (1, 99)):
        fp = SingleRowFastPredictor(trees, 1, bst.num_feature(),
                                    start_iteration=start,
                                    num_iteration=num)
        want = bst.predict(X[:6], start_iteration=start,
                           num_iteration=num, raw_score=True)
        got = np.array([fp(X[i], raw_score=True) for i in range(6)])
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)
    # booster entry point agrees with the predictor-level slicing
    fast = bst.predict_single_row_fast_init(start_iteration=1,
                                            num_iteration=3,
                                            raw_score=True)
    want = bst.predict(X[:4], start_iteration=1, num_iteration=3,
                       raw_score=True)
    np.testing.assert_allclose([fast(X[i]) for i in range(4)], want,
                               rtol=1e-12, atol=1e-12)


def test_prebind_multiclass_slicing():
    from lightgbm_tpu.predict_fast import SingleRowFastPredictor

    bst, X = _fit_model(objective="multiclass", num_class=3, cat=False)
    fp = SingleRowFastPredictor(bst._all_trees(), 3, bst.num_feature(),
                                start_iteration=1, num_iteration=2)
    want = bst.predict(X[:5], start_iteration=1, num_iteration=2,
                       raw_score=True)
    got = np.stack([fp(X[i], raw_score=True) for i in range(5)])
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


def test_single_row_latency_sub_ms():
    """The serving pin from the reference's FastPredict design: on a 5-tree
    model a pre-bound call must stay WELL under a millisecond (no device
    dispatch, no jit, no per-tree NumPy overhead)."""
    bst, X = _fit_model()
    fast = bst.predict_single_row_fast_init(raw_score=True)
    row = X[0]
    fast(row)                      # warm (builds nothing, but page in)
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        fast(row)
    per_call = (time.perf_counter() - t0) / reps
    assert per_call < 1e-3, f"{per_call*1e6:.0f} us/call"


def test_convert_output_np_matches_jax():
    """Every objective's NumPy serving transform must equal its jax
    convert_output (the single-row path must not dispatch jax per call)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objectives import create_objective

    rs = np.random.RandomState(0)
    for name, kc in [("regression", 1), ("poisson", 1), ("gamma", 1),
                     ("tweedie", 1), ("binary", 1), ("multiclass", 3),
                     ("multiclassova", 3), ("cross_entropy", 1),
                     ("cross_entropy_lambda", 1),
                     ("quantile", 1), ("huber", 1), ("fair", 1), ("mape", 1)]:
        params = {"objective": name, "sigmoid": 1.3}
        if kc > 1:
            params["num_class"] = kc
        obj = create_objective(Config.from_params(params))
        raw = rs.randn(40, kc).astype(np.float32) if kc > 1 \
            else rs.randn(40).astype(np.float32)
        a = np.asarray(obj.convert_output(raw))
        b = obj.convert_output_np(raw)
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7), name


def test_single_row_probability_no_jax(monkeypatch):
    """The non-raw fast path uses the NumPy transform end to end."""
    bst, X = _fit_model()
    fast = bst.predict_single_row_fast_init()
    p = fast(X[0])
    assert 0.0 < p < 1.0
    np.testing.assert_allclose(p, bst.predict(X[:1])[0], rtol=1e-6)
