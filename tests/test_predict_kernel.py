"""Device batch-prediction kernel vs the host predictor (interpret mode).

Reference analog: src/boosting/gbdt_prediction.cpp — batch predictions must
match the per-row walk."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Booster
from lightgbm_tpu.pallas import predict_kernel


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setattr(predict_kernel, "_INTERPRET", True)
    monkeypatch.setattr(Booster, "_DEVICE_PREDICT_MIN_ROWS", 100)
    yield


def _train(n=2000, f=8, seed=3, **params):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    X[rs.rand(n) < 0.1, 0] = np.nan
    y = X[:, 1] * 2 + np.nan_to_num(X[:, 0]) + 0.1 * rs.randn(n)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5, **params},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    return bst, X


def test_device_predict_matches_host():
    bst, X = _train()
    rs = np.random.RandomState(9)
    Xt = rs.randn(500, X.shape[1])
    Xt[rs.rand(500) < 0.1, 0] = np.nan
    p_dev = bst.predict(Xt)                       # device path (min rows 100)
    # force host path
    big = Booster._DEVICE_PREDICT_MIN_ROWS
    Booster._DEVICE_PREDICT_MIN_ROWS = 10 ** 9
    try:
        p_host = bst.predict(Xt)
    finally:
        Booster._DEVICE_PREDICT_MIN_ROWS = big
    np.testing.assert_allclose(p_dev, p_host, rtol=1e-4, atol=1e-5)


def test_device_predict_multiclass():
    rs = np.random.RandomState(5)
    X = rs.randn(1500, 6)
    y = (X[:, 0] + X[:, 1] > 0).astype(int) + (X[:, 2] > 0.5).astype(int)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbosity": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y.astype(float)), num_boost_round=4)
    p_dev = bst.predict(X)
    big = Booster._DEVICE_PREDICT_MIN_ROWS
    Booster._DEVICE_PREDICT_MIN_ROWS = 10 ** 9
    try:
        p_host = bst.predict(X)
    finally:
        Booster._DEVICE_PREDICT_MIN_ROWS = big
    assert p_dev.shape == (1500, 3)
    np.testing.assert_allclose(p_dev, p_host, rtol=1e-4, atol=1e-5)


def _train_cat(n=1200, seed=6):
    rs = np.random.RandomState(seed)
    X = 0.01 * rs.randn(n, 5)
    X[:, 3] = rs.randint(0, 6, n)
    y = 3.0 * np.isin(X[:, 3], [1, 4]).astype(float) + 0.01 * rs.randn(n)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "max_cat_to_onehot": 1},
                    lgb.Dataset(X, label=y, categorical_feature=[3]),
                    num_boost_round=3)
    use = bst._all_trees()
    has_cat_split = any(
        (np.asarray(t.decision_type[:max(t.num_leaves - 1, 0)]) & 1).any()
        for t in use)
    assert has_cat_split, "model should contain categorical splits"
    return bst, X, y


def test_device_predict_categorical_matches_host():
    """Categorical splits walk on-device (bin-domain bitset side table);
    NaN / unseen / negative category values re-bin to the always-zero
    sentinel bit, reproducing the host walk's route-right."""
    bst, X, y = _train_cat()
    use = bst._all_trees()
    Xt = X.copy()
    # adversarial category column: NaN, unseen, negative, fractional,
    # and far-out-of-range values on top of the seen 0..5
    rs = np.random.RandomState(8)
    n = len(Xt)
    Xt[rs.rand(n) < 0.1, 3] = np.nan
    Xt[rs.rand(n) < 0.05, 3] = 77.0          # unseen category
    Xt[rs.rand(n) < 0.05, 3] = -3.0          # negative -> missing
    Xt[rs.rand(n) < 0.05, 3] = 2.7           # truncates to category 2
    Xt[rs.rand(n) < 0.02, 3] = 1e12          # far past any bitset span
    p_dev = bst._try_device_predict(Xt, use, 1)
    assert p_dev is not None, "categorical model must take the device path"
    big = Booster._DEVICE_PREDICT_MIN_ROWS
    Booster._DEVICE_PREDICT_MIN_ROWS = 10 ** 9
    try:
        p_host = bst.predict(Xt, raw_score=True)
    finally:
        Booster._DEVICE_PREDICT_MIN_ROWS = big
    np.testing.assert_allclose(np.asarray(p_dev), p_host,
                               rtol=1e-4, atol=1e-5)
    p = bst.predict(X)
    assert np.corrcoef(p, y)[0, 1] > 0.9


def test_linear_tree_model_falls_back():
    rs = np.random.RandomState(6)
    X = rs.randn(900, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.01 * rs.randn(900)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "linear_tree": True},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    use = bst._all_trees()
    if not any(t.is_linear for t in use):
        import pytest
        pytest.skip("no linear trees were grown")
    assert bst._try_device_predict(X, use, 1) is None  # linear -> host


def test_device_predict_early_stop_matches_host():
    """pred_early_stop composes with the device batch walk (the kernel
    freezes cleared rows every es_freq trees — reference:
    prediction_early_stop.cpp CreateBinary) instead of forcing the host
    per-tree loop; outputs must match the host early-stop path."""
    rs = np.random.RandomState(11)
    n = 1200
    X = rs.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1] > 0)).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=20)
    kw = dict(raw_score=True, pred_early_stop=True,
              pred_early_stop_freq=4, pred_early_stop_margin=2.0)
    # device path taken: _try_device_predict returns non-None
    assert bst._try_device_predict(X, bst._all_trees(), 1,
                                   es=(4, 2.0)) is not None
    p_dev = bst.predict(X, **kw)
    big = Booster._DEVICE_PREDICT_MIN_ROWS
    Booster._DEVICE_PREDICT_MIN_ROWS = 10 ** 9
    try:
        p_host = bst.predict(X, **kw)
    finally:
        Booster._DEVICE_PREDICT_MIN_ROWS = big
    # early stopping must actually bite (outputs differ from full walk)
    p_full = bst.predict(X, raw_score=True)
    assert np.abs(p_host - p_full).max() > 1e-6
    np.testing.assert_allclose(p_dev, p_host, rtol=1e-4, atol=1e-5)


def test_device_predict_early_stop_multiclass_stays_host():
    """Multiclass margins couple classes; the device walk declines and the
    host loop keeps the reference's top1-top2 margin semantics."""
    rs = np.random.RandomState(5)
    X = rs.randn(600, 6)
    y = (X[:, 0] > 0).astype(int) + (X[:, 2] > 0.5).astype(int)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y.astype(float)), num_boost_round=6)
    assert bst._try_device_predict(X, bst._all_trees(), 3,
                                   es=(2, 0.5)) is None
    p = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=2,
                    pred_early_stop_margin=0.5)
    assert p.shape == (600, 3)
