"""Data & model quality observability (docs/OBSERVABILITY.md).

The quality contract under test:

  * the training-time :class:`QualityProfile` sidecar reconstructs the
    EXACT per-feature bin histograms of the binned matrix (EFB bundles
    unpacked, default bins recovered) and is chunk/rank-invariant —
    streamed and in-memory ingest write byte-identical profiles;
  * sidecar lifecycle degrades, never lies: a missing, corrupt, or
    sha-mismatched ``.quality.json`` loads as ``None`` (``available:
    false`` downstream) and never affects model loading or serving;
  * the drift monitor's multi-window state machine FIRES only when the
    fast AND slow windows both exceed the threshold, CLEARS on the fast
    window alone, and stays silent on in-distribution traffic;
  * the shadow audit re-scores served rows through the genuine
    ``Booster.predict`` host path and agrees BITWISE with what the wire
    returned;
  * ``/drift`` + ``/ready`` + ``/stats`` surface the state over HTTP,
    and the fleet report CLI merges per-replica snapshots.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import ModelRegistry, ServingApp
from lightgbm_tpu.telemetry.quality import (QUALITY_SUFFIX, QualityMonitor,
                                            QualityProfile, _coarsen,
                                            js_divergence, main,
                                            merge_reports, psi,
                                            quality_sidecar_path)

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5, "seed": 3}


def _make_data(seed=7, n=800, F=6):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, F)
    X[:, 4] = rs.randint(0, 9, n)
    X[rs.rand(n) < 0.15, 0] = np.nan
    y = ((X[:, 1] > 0) ^ (X[:, 4] == 3)).astype(np.float64)
    return X, y


def _train_to_file(path, seed=3, rounds=8):
    X, y = _make_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[4])
    Xv, yv = _make_data(seed=seed + 100, n=200)
    va = lgb.Dataset(Xv, label=yv, reference=ds)
    bst = lgb.train({**PARAMS, "seed": seed}, ds, num_boost_round=rounds,
                    valid_sets=[va], valid_names=["holdout"])
    bst.save_model(str(path))
    return X, bst


@pytest.fixture(scope="module")
def profiled(tmp_path_factory):
    """(model_path, X, booster) with a .quality.json sidecar on disk."""
    td = tmp_path_factory.mktemp("quality")
    mp = td / "model.txt"
    X, bst = _train_to_file(mp)
    return str(mp), X, bst


# ---------------------------------------------------------------------------
# drift math
# ---------------------------------------------------------------------------

def test_psi_identity_and_shift():
    assert psi([10, 20, 30], [10, 20, 30]) == 0.0
    assert psi([100, 0, 0], [0, 0, 100]) > 1.0
    # scale invariance: fractions, not counts
    assert psi([1, 2, 3], [10, 20, 30]) == pytest.approx(0.0, abs=1e-12)
    # degenerate inputs report "no signal", not an exception
    assert psi([0, 0], [5, 5]) == 0.0
    assert psi([], []) == 0.0


def test_js_divergence_bounds():
    assert js_divergence([5, 5], [5, 5]) == 0.0
    # disjoint support saturates at exactly 1 bit (base-2)
    assert js_divergence([10, 0], [0, 10]) == pytest.approx(1.0)
    d = js_divergence([30, 10], [10, 30])
    assert 0.0 < d < 1.0
    assert js_divergence([0], [0]) == 0.0


def test_coarsen_preserves_identity_and_mass():
    ref = np.arange(255, dtype=np.float64)
    rc, oc = _coarsen(ref, ref.copy())
    assert rc.shape[0] <= 16
    assert rc.sum() == ref.sum()
    assert psi(rc, oc) == 0.0
    # short histograms pass through untouched
    rc, oc = _coarsen(np.ones(8), np.ones(8))
    assert rc.shape == (8,)


def test_coarsen_controls_sampling_noise():
    """The reason coarsening exists: a 255-bin histogram sampled at a few
    hundred rows shows huge PSI from empty-bin flooring alone."""
    rs = np.random.RandomState(0)
    ref = np.bincount(rs.randint(0, 255, 100_000), minlength=255)
    obs = np.bincount(rs.randint(0, 255, 300), minlength=255)
    assert psi(ref, obs) > 1.0                    # fine bins: pure noise
    assert psi(*_coarsen(ref, obs)) < 0.2         # coarse: under threshold


# ---------------------------------------------------------------------------
# reference profile + sidecar lifecycle
# ---------------------------------------------------------------------------

def test_sidecar_written_and_linked(profiled):
    mp, X, bst = profiled
    sp = quality_sidecar_path(mp)
    assert sp == mp + QUALITY_SUFFIX and os.path.exists(sp)
    prof = QualityProfile.load(sp)
    assert prof.num_features == X.shape[1]
    assert prof.num_data == X.shape[0]
    import hashlib
    want = hashlib.sha256(
        open(mp, "rb").read().decode("utf-8").encode("utf-8")).hexdigest()
    assert prof.model_sha256 == want
    # holdout metric captured from the final evaluation
    assert prof.data["holdout_metric"]


def test_profile_counts_match_direct_binning(profiled):
    """EFB unpacking is exact: the profile's per-feature histograms equal
    re-binning the raw matrix through the profile's own mappers."""
    mp, X, bst = profiled
    prof = QualityProfile.load(quality_sidecar_path(mp))
    mappers = prof.mappers()
    for f, m in enumerate(mappers):
        nb = int(m.num_bins)
        want = np.bincount(
            np.asarray(m.transform(X[:, f]), dtype=np.int64),
            minlength=nb)
        got = prof.feature_counts(f)
        assert np.array_equal(got, want), f"feature {f}"
        assert int(got.sum()) == X.shape[0]


def test_profile_missing_rates(profiled):
    mp, X, _ = profiled
    prof = QualityProfile.load(quality_sidecar_path(mp))
    # feature 0 carries ~15% injected NaN; its missing bin agrees
    want = float(np.isnan(X[:, 0]).mean())
    assert prof.missing_rate(0) == pytest.approx(want)
    assert prof.missing_rate(1) == 0.0


def test_sidecar_degrades_never_lies(profiled, tmp_path):
    mp, X, _ = profiled
    import shutil
    mc = str(tmp_path / "m.txt")
    shutil.copy(mp, mc)
    sc = quality_sidecar_path(mc)

    # missing sidecar -> None, model loads and predicts
    model = ModelRegistry(mc, warmup=False).current()
    assert model.quality is None
    assert model.predict(X[:3]).shape == (3,)

    # corrupt sidecar -> None (not an exception)
    with open(sc, "w") as f:
        f.write("{definitely not json")
    model = ModelRegistry(mc, warmup=False).current()
    assert model.quality is None

    # poisoned sidecar (valid JSON, wrong model sha) -> None
    shutil.copy(quality_sidecar_path(mp), sc)
    prof = json.load(open(sc))
    prof["model_sha256"] = "0" * 64
    json.dump(prof, open(sc, "w"))
    model = ModelRegistry(mc, warmup=False).current()
    assert model.quality is None

    # healthy sidecar -> loaded and linked
    shutil.copy(quality_sidecar_path(mp), sc)
    model = ModelRegistry(mc, warmup=False).current()
    assert model.quality is not None
    assert model.quality.model_sha256 == model.sha256


def test_quality_profile_param_disables_sidecar(tmp_path):
    X, y = _make_data(n=300)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({**PARAMS, "quality_profile": False}, ds,
                    num_boost_round=3)
    mp = str(tmp_path / "noprof.txt")
    bst.save_model(mp)
    assert not os.path.exists(quality_sidecar_path(mp))


def test_profile_chunk_invariant_stream_vs_inmem(tmp_path):
    """The acceptance bar: streamed and in-memory ingest of the same CSV
    write byte-identical profiles (modulo the wall-clock stamp)."""
    rs = np.random.RandomState(5)
    X = np.round(rs.randn(2000, 5), 2)
    X[rs.rand(2000, 5) < 0.05] = np.nan
    y = (np.nan_to_num(X[:, 0]) > 0).astype(float)
    csv = str(tmp_path / "t.csv")
    with open(csv, "w") as f:
        for i in range(len(X)):
            f.write(f"{y[i]:.0f}," + ",".join(
                "" if np.isnan(v) else "%.17g" % v for v in X[i]) + "\n")
    p = {**PARAMS, "bin_construct_sample_cnt": 50000,
         "ingest_sketch_size": 65536}
    sidecars = {}
    for mode, chunk in (("inmem", None), ("stream", 700), ("stream", 333)):
        os.environ["LGBTPU_INGEST"] = mode
        if chunk:
            os.environ["LGBTPU_INGEST_CHUNK"] = str(chunk)
        try:
            bst = lgb.train(p, lgb.Dataset(csv, params=p),
                            num_boost_round=4)
        finally:
            os.environ.pop("LGBTPU_INGEST", None)
            os.environ.pop("LGBTPU_INGEST_CHUNK", None)
        mp = str(tmp_path / f"m_{mode}_{chunk}.txt")
        bst.save_model(mp)
        prof = json.load(open(quality_sidecar_path(mp)))
        prof.pop("created_unix")
        sidecars[(mode, chunk)] = json.dumps(prof, sort_keys=True)
    assert sidecars[("inmem", None)] == sidecars[("stream", 700)]
    assert sidecars[("stream", 700)] == sidecars[("stream", 333)]


# ---------------------------------------------------------------------------
# drift monitor state machine
# ---------------------------------------------------------------------------

def _monitor(model, **kw):
    clock = [0.0]
    kw.setdefault("threshold", 0.2)
    kw.setdefault("window_s", 8.0)
    kw.setdefault("sample", 1.0)
    kw.setdefault("audit_sample", 0.0)
    kw.setdefault("min_rows", 200)
    mon = QualityMonitor(clock=lambda: clock[0], **kw)
    mon.sync_model(model)
    return mon, clock


def _drive(mon, clock, model, make_batch, steps):
    for _ in range(steps):
        clock[0] += 1.0
        Xb = make_batch()
        mon.observe_batch(model, Xb, model.raw_scores(Xb))
        mon.tick(model=model)


def test_monitor_fire_and_clear(profiled):
    mp, X, _ = profiled
    model = ModelRegistry(mp, warmup=False).current()
    mon, clock = _monitor(model)
    rs = np.random.RandomState(1)

    def base():
        # match the TRAINING distribution, missing rate included — a
        # vanished NaN stream is itself drift the monitor would flag
        Xb = rs.randn(50, 6)
        Xb[:, 4] = rs.randint(0, 9, 50)
        Xb[rs.rand(50) < 0.15, 0] = np.nan
        return Xb

    # in-distribution traffic: never fires
    _drive(mon, clock, model, base, 120)
    snap = mon.snapshot()
    assert snap["available"] and mon.fired == 0 and not mon.alerting
    assert snap["drift"]["drift_fast"] < mon.threshold

    # covariate shift: fires once fast AND slow windows are both over
    _drive(mon, clock, model, lambda: base() + 5.0, 120)
    assert mon.alerting and mon.fired == 1
    snap = mon.snapshot()
    assert snap["drift"]["drift_fast"] >= mon.threshold
    assert snap["top_features"], "top-k drifted features surface"
    assert any(e["kind"] == "fire" for e in snap["timeline"])

    # recovery clears on the fast window alone (slow still elevated)
    _drive(mon, clock, model, base, 12)
    assert not mon.alerting and mon.cleared == 1
    assert mon.snapshot()["drift"]["drift_slow"] >= mon.threshold


def test_monitor_slow_window_gates_transients(profiled):
    """A short spike fills the fast window but not the slow one: no
    alert — the two-window AND is the flap guard."""
    mp, X, _ = profiled
    model = ModelRegistry(mp, warmup=False).current()
    mon, clock = _monitor(model)
    rs = np.random.RandomState(2)

    def base():
        Xb = rs.randn(50, 6)
        Xb[:, 4] = rs.randint(0, 9, 50)
        Xb[rs.rand(50) < 0.15, 0] = np.nan
        return Xb

    # long clean history dominates the slow window...
    _drive(mon, clock, model, base, 90)
    assert mon.fired == 0
    # ...then a 3-step spike saturates the fast window only
    _drive(mon, clock, model, lambda: base() + 9.0, 3)
    assert mon.snapshot()["drift"]["drift_fast"] >= mon.threshold
    assert mon.fired == 0 and not mon.alerting


def test_monitor_without_profile_reports_unavailable(profiled, tmp_path):
    mp, X, _ = profiled
    import shutil
    mc = str(tmp_path / "bare.txt")
    shutil.copy(mp, mc)
    model = ModelRegistry(mc, warmup=False).current()   # no sidecar
    assert model.quality is None
    mon, clock = _monitor(model)
    Xb = X[:50]
    mon.observe_batch(model, Xb, model.raw_scores(Xb))
    d = mon.tick(model=model)
    assert d == {"available": False}
    snap = mon.snapshot()
    assert snap["available"] is False
    assert "drift" not in snap           # no misreadable zeros
    assert "no quality sidecar" in snap["reason"]


def test_monitor_model_swap_resets(profiled, tmp_path):
    mp, X, _ = profiled
    model_a = ModelRegistry(mp, warmup=False).current()
    mon, clock = _monitor(model_a)
    rs = np.random.RandomState(3)
    _drive(mon, clock, model_a,
           lambda: rs.randn(60, 6) + 7.0, 120)
    assert mon.alerting
    mb = tmp_path / "model_b.txt"
    _train_to_file(mb, seed=11)
    model_b = ModelRegistry(str(mb), warmup=False).current()
    mon.sync_model(model_b)
    # new model: alert cleared, accumulators reset, profile adopted
    assert not mon.alerting and mon.cleared == 1
    snap = mon.snapshot()
    assert snap["available"] and snap["sampled_rows"] == 0
    assert snap["model_sha256"] == model_b.sha256
    assert any(e["kind"] == "model" for e in snap["timeline"])


# ---------------------------------------------------------------------------
# shadow audit
# ---------------------------------------------------------------------------

def test_shadow_audit_bitwise_agreement(profiled):
    mp, X, _ = profiled
    model = ModelRegistry(mp, warmup=False).current()
    mon = QualityMonitor(sample=0.0, audit_sample=1.0)
    for off in range(0, 200, 25):
        rows = X[off:off + 25]
        raw = model.raw_scores(rows)
        mon.offer_audit(model, rows, raw, False, f"t-{off}")
    n = mon.audit_once(max_entries=1000)
    assert n == 200
    snap = mon.snapshot()
    assert snap["audit"]["rows"] == 200
    assert snap["audit"]["mismatches"] == 0
    assert snap["audit"]["pending"] == 0


def test_shadow_audit_detects_tampering(profiled):
    mp, X, _ = profiled
    model = ModelRegistry(mp, warmup=False).current()
    mon = QualityMonitor(sample=0.0, audit_sample=1.0)
    rows = X[:10]
    raw = np.asarray(model.raw_scores(rows), dtype=np.float64).copy()
    raw[0] += 1e-9            # one ULP-scale lie on the wire
    mon.offer_audit(model, rows, raw, True, "t-x")
    mon.audit_once()
    assert mon.snapshot()["audit"]["mismatches"] == 1


def test_shadow_audit_ring_is_bounded(profiled):
    mp, X, _ = profiled
    model = ModelRegistry(mp, warmup=False).current()
    mon = QualityMonitor(sample=0.0, audit_sample=1.0, audit_capacity=3)
    raw = model.raw_scores(X[:2])
    for _ in range(5):
        mon.offer_audit(model, X[:2], raw, False, None)
    snap = mon.snapshot()
    assert snap["audit"]["pending"] == 3
    assert snap["audit"]["dropped"] == 2


# ---------------------------------------------------------------------------
# serving surface: /drift, /ready, /stats, access log, fleet report
# ---------------------------------------------------------------------------

@pytest.fixture
def telemetry():
    from lightgbm_tpu import telemetry as tel
    tel.reset()
    tel.configure(enabled=True)
    yield tel
    tel.disable()
    tel.reset()
    tel.configure(enabled=False, metrics_out="", trace_out="")


def test_server_quality_surface(profiled, telemetry):
    from tests.test_serving import _get, _post
    mp, X, _ = profiled
    app = ServingApp(mp, port=0, max_batch=32, max_delay_ms=1.0,
                     quality_sample=1.0, quality_audit_sample=1.0,
                     quality_min_rows=100).start()
    try:
        host, port = app.host, app.port
        for off in range(0, 300, 30):
            st, obj = _post(host, port, "/predict",
                            {"rows": X[off:off + 30].tolist()})
            assert st == 200
        app.quality.tick(model=app.registry.current())
        audited = app.quality.audit_once(max_entries=1000)
        assert audited > 0, "batcher hook feeds the audit ring"

        st, drift = _get(host, port, "/drift")
        assert st == 200
        assert drift["available"] is True
        assert drift["sampled_rows"] >= 300
        assert drift["audit"]["rows"] == audited
        assert drift["audit"]["mismatches"] == 0
        assert drift["model_sha256"] == app.registry.current().sha256

        # /stats carries the compact quality block
        st, stats = _get(host, port, "/stats")
        assert stats["quality"]["available"] is True
        assert stats["quality"]["alerting"] is False

        # /ready: a drift alert surfaces as a degraded reason but does
        # NOT flip readiness (drift is a quality problem, not an outage)
        st, ready = _get(host, port, "/ready")
        assert st == 200 and "drift_alert" not in ready
        app.quality.alerting = True
        try:
            st, ready = _get(host, port, "/ready")
            assert st == 200 and ready["ready"] is True
            assert ready["drift_alert"] is True
            assert "data drift" in ready["degraded"]
        finally:
            app.quality.alerting = False

        # prometheus gauges flow through the existing /metrics endpoint
        st, _ = _post(host, port, "/predict", {"rows": X[:2].tolist()})
        conn = __import__("http.client", fromlist=["x"]).HTTPConnection(
            host, port, timeout=10)
        try:
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
        finally:
            conn.close()
        assert "drift_available 1" in text.replace(".0", "")
        assert "quality_audit_rows" in text
    finally:
        app.shutdown()


def test_hot_reload_carries_sidecar(profiled, tmp_path):
    """/reload to a new model adopts ITS sidecar (and resets the
    monitor); reloading a model without one degrades to available:false
    while serving continues."""
    from tests.test_serving import _get, _post
    mp, X, _ = profiled
    mb = str(tmp_path / "model_b.txt")
    _train_to_file(mb, seed=11)
    bare = str(tmp_path / "bare.txt")
    _train_to_file(bare, seed=23)         # a third model...
    os.remove(quality_sidecar_path(bare))   # ...without its sidecar
    app = ServingApp(mp, port=0, max_batch=16, max_delay_ms=1.0,
                     quality_sample=1.0).start()
    try:
        host, port = app.host, app.port
        sha_a = app.registry.current().sha256
        app.quality.tick(model=app.registry.current())
        st, d = _get(host, port, "/drift")
        assert d["available"] and d["model_sha256"] == sha_a

        st, obj = _post(host, port, "/reload", {"path": mb})
        assert st == 200
        app.quality.tick(model=app.registry.current())
        st, d = _get(host, port, "/drift")
        assert d["available"] is True
        assert d["model_sha256"] == app.registry.current().sha256 != sha_a
        assert d["sampled_rows"] == 0     # accumulators reset on swap

        st, obj = _post(host, port, "/reload", {"path": bare})
        assert st == 200
        app.quality.tick(model=app.registry.current())
        st, d = _get(host, port, "/drift")
        assert d["available"] is False and "reason" in d
        st, obj = _post(host, port, "/predict", {"rows": X[:2].tolist()})
        assert st == 200                  # no sidecar != not serving
    finally:
        app.shutdown()


def test_promotion_carries_sidecar(profiled, tmp_path):
    """The promotion pointer hands replicas a model PATH; the registry
    load of that path picks the sidecar up with no fleet involvement."""
    from lightgbm_tpu.serving.fleet import promote_pointer, read_pointer
    mp, X, _ = profiled
    d = str(tmp_path)
    promote_pointer(d, mp)
    target = read_pointer(d)["path"]
    model = ModelRegistry(target, warmup=False).current()
    assert model.quality is not None
    assert model.quality.model_sha256 == model.sha256
    # a poisoned sidecar on the promoted path: replica still loads+serves
    sc = quality_sidecar_path(target)
    prof = json.load(open(sc))
    prof["model_sha256"] = "f" * 64
    json.dump(prof, open(sc, "w"))
    try:
        model = ModelRegistry(target, warmup=False).current()
        assert model.quality is None
        assert model.predict(X[:2]).shape == (2,)
    finally:
        json.dump({**prof, "model_sha256": model.sha256}, open(sc, "w"))


def test_fleet_report_cli_merges_replicas(tmp_path, capsys):
    fleet_dir = str(tmp_path)
    for rank, (alerting, rows) in enumerate([(False, 100), (True, 50)]):
        snap = {"available": True, "alerting": alerting,
                "model_sha256": "ab" * 32,
                "audit": {"rows": rows, "mismatches": rank, "pending": 0,
                          "dropped": 0},
                "top_features": [{"feature": 2, "psi_fast": 0.5 + rank}],
                "sampled_rows": rows}
        with open(os.path.join(fleet_dir,
                               f"drift_replica_{rank}.json"), "w") as f:
            json.dump(snap, f)
    rep = merge_reports(fleet_dir)
    assert rep["available"] and rep["any_alerting"]
    assert rep["replicas"]["0"]["alerting"] is False
    assert rep["replicas"]["1"]["alerting"] is True
    assert rep["audit"]["rows"] == 150
    assert rep["audit"]["mismatches"] == 1
    # per-feature max across replicas, not a sum
    assert rep["top_features"][0] == {"feature": 2, "max_psi": 1.5}

    assert main(["report", fleet_dir]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["audit"]["rows"] == 150
    # empty dir: NOTICE + nonzero, so a cron can tell "no data" apart
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert main(["report", empty]) == 1
