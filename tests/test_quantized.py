"""Quantized-gradient training (reference: gradient_discretizer.cpp,
use_quantized_grad / num_grad_quant_bins / quant_train_renew_leaf /
stochastic_rounding config)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=3000, seed=8):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 8)
    logit = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2]
    y = (rs.rand(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
        "min_data_in_leaf": 5, "max_bin": 63}


def _auc(y, p):
    order = np.argsort(p)
    r = np.empty(len(p))
    r[order] = np.arange(len(p))
    npos = y.sum()
    return (r[y > 0.5].sum() - npos * (npos - 1) / 2) / (npos * (len(y) - npos))


@pytest.mark.slow
def test_quantized_close_to_fp32():
    X, y = _data()
    b_fp = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=20)
    b_q = lgb.train({**BASE, "use_quantized_grad": True},
                    lgb.Dataset(X, label=y), num_boost_round=20)
    auc_fp = _auc(y, b_fp.predict(X))
    auc_q = _auc(y, b_q.predict(X))
    assert auc_q > auc_fp - 0.01, (auc_q, auc_fp)


def test_quantized_renew_leaf():
    X, y = _data(seed=9)
    b = lgb.train({**BASE, "use_quantized_grad": True,
                   "quant_train_renew_leaf": True},
                  lgb.Dataset(X, label=y), num_boost_round=15)
    assert _auc(y, b.predict(X)) > 0.8


@pytest.mark.slow
def test_quantized_bins_and_rounding_params():
    X, y = _data(seed=10)
    for extra in ({"num_grad_quant_bins": 16},
                  {"stochastic_rounding": False}):
        b = lgb.train({**BASE, "use_quantized_grad": True, **extra},
                      lgb.Dataset(X, label=y), num_boost_round=10)
        assert _auc(y, b.predict(X)) > 0.75


# ---------------------------------------------------------------------------
# packed histogram wire widths (hist_packed_width; PR "histogram floor")
# ---------------------------------------------------------------------------

def test_packed_width_requires_quantized():
    import pytest as _pytest
    from lightgbm_tpu.utils.log import LightGBMError
    X, y = _data(seed=11)
    with _pytest.raises(LightGBMError, match="use_quantized_grad"):
        lgb.train({**BASE, "hist_packed_width": 16},
                  lgb.Dataset(X, label=y), num_boost_round=1)


def test_packed_widths_train_and_stay_accurate():
    """hist_packed_width only changes the MESH collective wire; on a single
    device it must be a byte-level no-op, and every width must keep the
    quantized model usable (mesh wire identity: test_hist_backends.py)."""
    X, y = _data(seed=12)
    ref = None
    for w in (32, 16, 8):
        b = lgb.train({**BASE, "use_quantized_grad": True,
                       "num_grad_quant_bins": 16, "hist_packed_width": w},
                      lgb.Dataset(X, label=y), num_boost_round=10)
        assert _auc(y, b.predict(X)) > 0.8
        s = b.model_to_string().split("\nparameters:")[0]
        if ref is None:
            ref = s
        else:
            assert s == ref, f"width {w} changed a single-device model"
