"""Ranking objective/metric tests (model: reference test_engine.py lambdarank tests)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import make_synthetic_ranking


def _ndcg_at(scores, labels, qb, k=5):
    nq = len(qb) - 1
    vals = []
    for qi in range(nq):
        s, e = qb[qi], qb[qi + 1]
        sc, lb = scores[s:e], labels[s:e]
        order = np.argsort(-sc)
        gains = 2.0 ** lb - 1.0
        disc = 1.0 / np.log2(np.arange(len(sc)) + 2.0)
        dcg = np.sum(gains[order][:k] * disc[:k])
        ideal = np.sum(np.sort(gains)[::-1][:k] * disc[:k])
        if ideal > 0:
            vals.append(dcg / ideal)
    return float(np.mean(vals))


def test_lambdarank_improves_ndcg():
    X, y, sizes = make_synthetic_ranking(nq=120)
    ds = lgb.Dataset(X, label=y, group=sizes)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
                     "metric": "ndcg", "eval_at": [5]},
                    ds, num_boost_round=30)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    pred = bst.predict(X, raw_score=True)
    ndcg_trained = _ndcg_at(pred, y, qb)
    rs = np.random.RandomState(0)
    ndcg_random = _ndcg_at(rs.randn(len(y)), y, qb)
    assert ndcg_trained > ndcg_random + 0.15
    assert ndcg_trained > 0.75


@pytest.mark.slow
def test_rank_xendcg():
    X, y, sizes = make_synthetic_ranking(nq=120, seed=3)
    ds = lgb.Dataset(X, label=y, group=sizes)
    bst = lgb.train({"objective": "rank_xendcg", "num_leaves": 15, "verbosity": -1},
                    ds, num_boost_round=30)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    pred = bst.predict(X, raw_score=True)
    assert _ndcg_at(pred, y, qb) > 0.7


def test_ndcg_metric_reported():
    X, y, sizes = make_synthetic_ranking(nq=80)
    ds = lgb.Dataset(X, label=y, group=sizes)
    valid = ds.create_valid(X, label=y, group=sizes)
    evals = {}
    lgb.train({"objective": "lambdarank", "verbosity": -1, "eval_at": [1, 3, 5],
               "num_leaves": 15},
              ds, num_boost_round=10, valid_sets=[valid],
              callbacks=[lgb.record_evaluation(evals)])
    assert "ndcg@1" in evals["valid_0"]
    assert "ndcg@5" in evals["valid_0"]
    assert evals["valid_0"]["ndcg@5"][-1] >= evals["valid_0"]["ndcg@5"][0] - 0.05


def test_lambdarank_ranker_sklearn():
    X, y, sizes = make_synthetic_ranking(nq=100)
    m = lgb.LGBMRanker(n_estimators=20, num_leaves=15, verbosity=-1)
    m.fit(X, y, group=sizes)
    pred = m.predict(X)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    assert _ndcg_at(pred, y, qb) > 0.7


def test_bagging_by_query():
    X, y, sizes = make_synthetic_ranking(nq=60)
    ds = lgb.Dataset(X, label=y, group=sizes)
    bst = lgb.train({"objective": "lambdarank", "verbosity": -1,
                     "bagging_by_query": True, "bagging_fraction": 0.5,
                     "bagging_freq": 1, "num_leaves": 15}, ds,
                    num_boost_round=8)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    scores = bst.predict(X)
    assert _ndcg_at(scores, y, qb) > 0.5


@pytest.mark.slow
def test_cv_lambdarank_group_propagation():
    X, y, sizes = make_synthetic_ranking(nq=60)
    ds = lgb.Dataset(X, label=y, group=sizes)
    res = lgb.cv({"objective": "lambdarank", "verbosity": -1, "num_leaves": 15},
                 ds, num_boost_round=5, nfold=3)
    assert any("ndcg" in k for k in res)
