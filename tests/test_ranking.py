"""Ranking objective/metric tests (model: reference test_engine.py lambdarank tests)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import make_synthetic_ranking


def _ndcg_at(scores, labels, qb, k=5):
    nq = len(qb) - 1
    vals = []
    for qi in range(nq):
        s, e = qb[qi], qb[qi + 1]
        sc, lb = scores[s:e], labels[s:e]
        order = np.argsort(-sc)
        gains = 2.0 ** lb - 1.0
        disc = 1.0 / np.log2(np.arange(len(sc)) + 2.0)
        dcg = np.sum(gains[order][:k] * disc[:k])
        ideal = np.sum(np.sort(gains)[::-1][:k] * disc[:k])
        if ideal > 0:
            vals.append(dcg / ideal)
    return float(np.mean(vals))


def test_lambdarank_improves_ndcg():
    X, y, sizes = make_synthetic_ranking(nq=120)
    ds = lgb.Dataset(X, label=y, group=sizes)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
                     "metric": "ndcg", "eval_at": [5]},
                    ds, num_boost_round=30)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    pred = bst.predict(X, raw_score=True)
    ndcg_trained = _ndcg_at(pred, y, qb)
    rs = np.random.RandomState(0)
    ndcg_random = _ndcg_at(rs.randn(len(y)), y, qb)
    assert ndcg_trained > ndcg_random + 0.15
    assert ndcg_trained > 0.75


@pytest.mark.slow
def test_rank_xendcg():
    X, y, sizes = make_synthetic_ranking(nq=120, seed=3)
    ds = lgb.Dataset(X, label=y, group=sizes)
    bst = lgb.train({"objective": "rank_xendcg", "num_leaves": 15, "verbosity": -1},
                    ds, num_boost_round=30)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    pred = bst.predict(X, raw_score=True)
    assert _ndcg_at(pred, y, qb) > 0.7


def test_ndcg_metric_reported():
    X, y, sizes = make_synthetic_ranking(nq=80)
    ds = lgb.Dataset(X, label=y, group=sizes)
    valid = ds.create_valid(X, label=y, group=sizes)
    evals = {}
    lgb.train({"objective": "lambdarank", "verbosity": -1, "eval_at": [1, 3, 5],
               "num_leaves": 15},
              ds, num_boost_round=10, valid_sets=[valid],
              callbacks=[lgb.record_evaluation(evals)])
    assert "ndcg@1" in evals["valid_0"]
    assert "ndcg@5" in evals["valid_0"]
    assert evals["valid_0"]["ndcg@5"][-1] >= evals["valid_0"]["ndcg@5"][0] - 0.05


def test_lambdarank_ranker_sklearn():
    X, y, sizes = make_synthetic_ranking(nq=100)
    m = lgb.LGBMRanker(n_estimators=20, num_leaves=15, verbosity=-1)
    m.fit(X, y, group=sizes)
    pred = m.predict(X)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    assert _ndcg_at(pred, y, qb) > 0.7


def test_bagging_by_query():
    X, y, sizes = make_synthetic_ranking(nq=60)
    ds = lgb.Dataset(X, label=y, group=sizes)
    bst = lgb.train({"objective": "lambdarank", "verbosity": -1,
                     "bagging_by_query": True, "bagging_fraction": 0.5,
                     "bagging_freq": 1, "num_leaves": 15}, ds,
                    num_boost_round=8)
    qb = np.concatenate([[0], np.cumsum(sizes)])
    scores = bst.predict(X)
    assert _ndcg_at(scores, y, qb) > 0.5


@pytest.mark.slow
def test_cv_lambdarank_group_propagation():
    X, y, sizes = make_synthetic_ranking(nq=60)
    ds = lgb.Dataset(X, label=y, group=sizes)
    res = lgb.cv({"objective": "lambdarank", "verbosity": -1, "num_leaves": 15},
                 ds, num_boost_round=5, nfold=3)
    assert any("ndcg" in k for k in res)


def _brute_lambdarank(scores, labels, gains, imd, sigma, norm, trunc):
    """Direct transliteration of GetGradientsForOneQuery's pair loop
    (reference: rank_objective.hpp:180): docs sorted by score desc (stable),
    pairs (i, j) with i in the top `trunc` sorted positions, j after i,
    labels different; the higher-labelled doc gets +lambda."""
    cnt = len(scores)
    order = np.argsort(-scores, kind="stable")
    g = np.zeros(cnt)
    h = np.zeros(cnt)
    sum_lam = 0.0
    best, worst = scores.max(), scores.min()
    disc = lambda pos: 1.0 / np.log2(pos + 2.0)
    for ai in range(min(trunc, cnt)):
        i = order[ai]
        for bj in range(ai + 1, cnt):
            j = order[bj]
            if labels[i] == labels[j]:
                continue
            if labels[i] > labels[j]:
                hi, lo, dh, dl = i, j, disc(ai), disc(bj)
            else:
                hi, lo, dh, dl = j, i, disc(bj), disc(ai)
            delta = abs(gains[hi] - gains[lo]) * abs(dh - dl) * imd
            sd = scores[hi] - scores[lo]
            if norm and best != worst:
                delta /= (0.01 + abs(sd))
            p = 1.0 / (1.0 + np.exp(sigma * sd))
            lam = -sigma * p * delta
            hs = sigma * sigma * p * (1 - p) * delta
            g[hi] += lam
            g[lo] -= lam
            h[hi] += hs
            h[lo] += hs
            sum_lam += -2 * lam
    if norm and sum_lam > 0:
        f = np.log2(1 + sum_lam) / sum_lam
        g *= f
        h *= f
    return g, h


@pytest.mark.parametrize("norm", [True, False])
def test_lambdarank_gradients_match_pair_loop(norm):
    """The sorted-space top-K tensor formulation must reproduce the
    reference's per-query pair loop exactly (f32 tolerance)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ranking import _lambdarank_bucket

    rs = np.random.RandomState(3)
    Q, M, trunc, sigma = 11, 24, 7, 1.3
    sizes = rs.randint(3, M + 1, Q)
    scores = np.zeros((Q, M), np.float32)
    labels = np.zeros((Q, M), np.float32)
    valid = np.zeros((Q, M), bool)
    gains = np.zeros((Q, M), np.float32)
    imd = np.zeros(Q, np.float32)
    g_ref = np.zeros((Q, M))
    h_ref = np.zeros((Q, M))
    for q in range(Q):
        n = sizes[q]
        s = np.round(rs.randn(n) * 2, 1).astype(np.float32)  # score ties
        lab = rs.randint(0, 4, n).astype(np.float32)
        gn = (2.0 ** lab - 1).astype(np.float32)
        md = np.sort(gn)[::-1][:trunc].dot(
            1 / np.log2(np.arange(2, 2 + min(trunc, n))))
        im = 1.0 / max(md, 1e-9)
        scores[q, :n], labels[q, :n], valid[q, :n] = s, lab, True
        gains[q, :n], imd[q] = gn, im
        g_ref[q, :n], h_ref[q, :n] = _brute_lambdarank(
            s.astype(np.float64), lab, gn, im, sigma, norm, trunc)
    g, h = _lambdarank_bucket(jnp.asarray(scores), jnp.asarray(labels),
                              jnp.asarray(valid), jnp.asarray(imd),
                              jnp.asarray(gains), sigma=sigma, norm=norm,
                              trunc=trunc, chunk=8)
    np.testing.assert_allclose(np.asarray(g), g_ref, atol=2e-6)
    np.testing.assert_allclose(np.asarray(h), h_ref, atol=2e-6)
