"""Fault-tolerance suite (docs/ROBUSTNESS.md).

Covers the checkpoint/resume bit-identity contract, manifest validation of
corrupt/truncated snapshots, the nan_guard policy paths, the chaos harness
no-op guarantee, and (slow tier) the supervising distributed launcher:
fail-fast on worker crash, hang detection via stale heartbeats, and
kill -> relaunch -> resume recovery within ``dist_retries``.
"""
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import LightGBMError
from lightgbm_tpu.robustness import chaos, checkpoint
from lightgbm_tpu.robustness.checkpoint import (latest_valid_snapshot,
                                                list_snapshots,
                                                validate_checkpoint)

from conftest import (make_synthetic_binary, make_synthetic_multiclass,
                      make_synthetic_ranking)

REPO = Path(__file__).resolve().parent.parent


def _binary_params(output_model, **extra):
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1, "snapshot_freq": 4, "output_model": str(output_model)}
    p.update(extra)
    return p


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

def test_resume_bit_identity_binary(tmp_path):
    X, y = make_synthetic_binary(n=1200)
    M = tmp_path / "out" / "model.txt"       # exercises dir creation too
    params = _binary_params(M)
    full = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    snap = str(M) + ".snapshot_iter_4"
    assert os.path.exists(snap)
    assert os.path.exists(snap + ".manifest.json")
    resumed = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                        resume_from=snap)
    assert resumed.model_to_string() == full.model_to_string()


@pytest.mark.slow
def test_resume_bit_identity_multiclass_batched(tmp_path):
    X, y = make_synthetic_multiclass(n=1500, k=4)
    M = tmp_path / "mc.txt"
    params = {"objective": "multiclass", "num_class": 4, "num_leaves": 12,
              "verbosity": -1, "snapshot_freq": 3, "output_model": str(M),
              "multiclass_batched": True}
    full = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    assert full.engine._mc_batched_last   # the widened lockstep path ran
    resumed = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6,
                        resume_from=str(M) + ".snapshot_iter_3")
    assert resumed.model_to_string() == full.model_to_string()


def test_resume_with_bagging_and_feature_fraction(tmp_path):
    """Per-iteration RNG consumers (bagging keys, the feature-fraction
    host RandomState) must continue exactly where the snapshot left off."""
    X, y = make_synthetic_binary(n=1500)
    M = tmp_path / "bag.txt"
    params = _binary_params(M, bagging_fraction=0.7, bagging_freq=2,
                            feature_fraction=0.8)
    full = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    resumed = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                        resume_from=str(M) + ".snapshot_iter_4")
    assert resumed.model_to_string() == full.model_to_string()


def test_corrupt_checkpoint_rejected(tmp_path):
    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "model.txt"
    params = _binary_params(M)
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    snap = str(M) + ".snapshot_iter_4"
    text = open(snap).read()
    open(snap, "w").write(text[:len(text) // 2])
    with pytest.raises(LightGBMError, match="checksum"):
        validate_checkpoint(snap)
    with pytest.raises(LightGBMError, match="checksum"):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                  resume_from=snap)


def test_missing_manifest_rejected(tmp_path):
    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "model.txt"
    params = _binary_params(M)
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    plain = tmp_path / "plain_model.txt"
    bst.save_model(str(plain))               # a model file, not a checkpoint
    with pytest.raises(LightGBMError, match="manifest"):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                  resume_from=str(plain))


def test_resume_params_mismatch_rejected(tmp_path):
    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "model.txt"
    lgb.train(_binary_params(M), lgb.Dataset(X, label=y), num_boost_round=4)
    snap = str(M) + ".snapshot_iter_4"
    bad = _binary_params(M, learning_rate=0.27)
    with pytest.raises(LightGBMError, match="learning_rate"):
        lgb.train(bad, lgb.Dataset(X, label=y), num_boost_round=8,
                  resume_from=snap)


def test_resume_and_init_model_conflict(tmp_path):
    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "model.txt"
    params = _binary_params(M)
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    with pytest.raises(LightGBMError, match="not both"):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                  resume_from=str(M) + ".snapshot_iter_4", init_model=bst)


def test_snapshot_prune_and_atomicity(tmp_path):
    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "snapdir" / "model.txt"
    params = _binary_params(M, snapshot_freq=2, snapshot_keep=2)
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    snaps = list_snapshots(str(M))
    assert [it for it, _ in snaps] == [6, 8]       # pruned to the 2 newest
    leftovers = [p for p in os.listdir(M.parent) if ".tmp." in p]
    assert leftovers == []                         # tmp files always cleaned
    for _, p in snaps:
        assert os.path.exists(p + ".manifest.json")
        assert os.path.exists(p + ".state.npz")


def test_truncated_model_string_rejected(tmp_path):
    X, y = make_synthetic_binary(n=800)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    s = bst.model_to_string()
    with pytest.raises(LightGBMError, match="truncated model"):
        lgb.Booster(model_str=s[:int(len(s) * 0.5)])
    # cutting before the marker but after all trees must also be caught
    cut = s[:s.index("end of trees")]
    with pytest.raises(LightGBMError, match="end of trees"):
        lgb.Booster(model_str=cut)


def test_nonfinite_init_model_rejected(tmp_path):
    X, y = make_synthetic_binary(n=800)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    s = bst.model_to_string()
    lines = s.split("\n")
    for i, ln in enumerate(lines):
        if ln.startswith("leaf_value="):
            vals = ln[len("leaf_value="):].split(" ")
            vals[0] = "nan"
            lines[i] = "leaf_value=" + " ".join(vals)
            break
    poisoned = lgb.Booster(model_str="\n".join(lines))
    with pytest.raises(LightGBMError):
        lgb.train({"objective": "binary", "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2,
                  init_model=poisoned)


# ---------------------------------------------------------------------------
# nan_guard
# ---------------------------------------------------------------------------

def test_nan_guard_warn_skips_poisoned_iteration(tmp_path, monkeypatch):
    X, y = make_synthetic_binary(n=1000)
    monkeypatch.setenv(chaos.ENV_VAR, "nan_grad:iter=3")
    params = {"objective": "binary", "verbosity": -1, "telemetry": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    assert bst.engine.nan_iterations == 1
    assert bst.num_trees() == 6               # skipped iter keeps a no-op tree
    lm = lgb.Booster(model_str=bst.model_to_string())._loaded_trees
    assert all(np.isfinite(t.leaf_value).all() for t in lm.trees)
    trees = lm.trees
    assert trees[2].num_leaves == 1 and float(trees[2].leaf_value[0]) == 0.0
    counters = lgb.telemetry.global_registry.snapshot()["counters"]
    assert counters.get("train/nan_skipped") == 1


def test_nan_guard_raise(monkeypatch):
    X, y = make_synthetic_binary(n=1000)
    monkeypatch.setenv(chaos.ENV_VAR, "nan_grad:iter=2")
    with pytest.raises(LightGBMError, match="nan_guard=raise"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "nan_guard": "raise"},
                  lgb.Dataset(X, label=y), num_boost_round=6)


def test_nan_guard_invalid_mode():
    X, y = make_synthetic_binary(n=200)
    with pytest.raises(ValueError, match="nan_guard"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "nan_guard": "explode"},
                  lgb.Dataset(X, label=y), num_boost_round=1)


def test_nan_guard_keeps_objective_state(monkeypatch):
    """A skipped iteration must also keep the objective's PREVIOUS
    per-iteration state: lambdarank's position-bias update is computed from
    the poisoned lambdas, and writing it back would re-poison every later
    iteration's gradients."""
    X, y, sizes = make_synthetic_ranking(nq=60)
    rs = np.random.RandomState(0)
    pos = np.concatenate([np.arange(s) % 10 for s in sizes])
    monkeypatch.setenv(chaos.ENV_VAR, "nan_grad:iter=2")
    bst = lgb.train({"objective": "lambdarank",
                     "lambdarank_position_bias_regularization": 0.1,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y, group=sizes, position=pos),
                    num_boost_round=5)
    assert bst.engine.nan_iterations == 1
    assert np.isfinite(np.asarray(bst.engine.objective.pos_biases)).all()
    assert np.isfinite(np.asarray(bst.engine.score)).all()


def test_nan_guard_init_score(monkeypatch):
    X, y = make_synthetic_binary(n=400)
    init = np.zeros(len(y))
    init[7] = np.nan
    with pytest.raises(LightGBMError, match="init_score"):
        lgb.train({"objective": "binary", "verbosity": -1,
                   "nan_guard": "raise"},
                  lgb.Dataset(X, label=y, init_score=init), num_boost_round=2)
    # warn mode: non-finite entries zeroed, training proceeds finite
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y, init_score=init),
                    num_boost_round=2)
    assert np.isfinite(bst.predict(X, raw_score=True)).all()


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------

def test_chaos_noop_when_env_unset(monkeypatch):
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    assert not chaos.active()
    assert not chaos.has("kill")
    chaos.maybe_kill(1)                        # must not exit
    chaos.heartbeat_hook(1)                    # must not sleep/hang
    import jax.numpy as jnp
    g = jnp.arange(4.0)
    assert chaos.inject_nan_grad(g, 1) is g    # exact pass-through


def test_chaos_parse_and_cli(monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR,
                       "kill:iter=5,rank=1,once=/tmp/m; nan_grad:iter=3,count=4")
    ds = chaos.directives()
    assert [d.name for d in ds] == ["kill", "nan_grad"]
    assert ds[0].iteration == 5 and ds[0].rank == 1 and ds[0].once == "/tmp/m"
    assert ds[1].count == 4
    assert chaos.main() == 0
    monkeypatch.setenv(chaos.ENV_VAR, "kill:bogus_key=1")
    with pytest.raises(ValueError, match="unknown option"):
        chaos.directives()


def test_chaos_closed_loop_directives(monkeypatch, tmp_path):
    """The refit/promotion chaos hooks (docs/ROBUSTNESS.md chaos matrix):
    poison_refit NaNs leaf values, torn_pointer half-writes promote.json,
    and all three parse with the standard option grammar."""
    monkeypatch.setenv(
        chaos.ENV_VAR,
        "poison_refit:iter=1,count=3; kill_refit:once=/tmp/m; "
        "torn_pointer:once=/tmp/m2")
    ds = chaos.directives()
    assert [d.name for d in ds] == ["poison_refit", "kill_refit",
                                    "torn_pointer"]
    assert ds[0].count == 3 and ds[1].once == "/tmp/m"
    vals = np.linspace(-1.0, 1.0, 8)
    poisoned = chaos.inject_nan_refit(vals, tree_index=1)
    assert np.isnan(poisoned[:3]).all() and np.isfinite(poisoned[3:]).all()
    assert np.isfinite(vals).all()             # input untouched
    # unmatched tree index: exact pass-through
    assert chaos.inject_nan_refit(vals, tree_index=2) is vals
    monkeypatch.delenv(chaos.ENV_VAR)
    assert chaos.inject_nan_refit(vals, tree_index=1) is vals
    assert chaos.maybe_tear_pointer(str(tmp_path), "{}") is False
    chaos.maybe_kill_refit()                   # must not exit


def test_prune_never_deletes_promoted_snapshot(tmp_path):
    """snapshot_keep pruning must skip any snapshot a live promote.json
    generation points at — current target or rollback target — else a
    replica restart/rollback would load a deleted file."""
    from lightgbm_tpu.robustness.checkpoint import prune_snapshots
    from lightgbm_tpu.serving.fleet import promote_pointer

    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "model.txt"
    fleet = tmp_path / "fleet"
    fleet.mkdir()
    lgb.train(_binary_params(M, snapshot_freq=2),
              lgb.Dataset(X, label=y), num_boost_round=8)
    snaps = dict(list_snapshots(str(M)))
    assert set(snaps) == {2, 4, 6, 8}
    # promote iter-2 (-> prev of nothing), then iter-4: the pointer now
    # pins 4 (current) AND 2 (rollback target)
    promote_pointer(str(fleet), snaps[2])
    promote_pointer(str(fleet), snaps[4])
    prune_snapshots(str(M), keep=1, fleet_dir=str(fleet))
    kept = set(dict(list_snapshots(str(M))))
    assert kept == {2, 4, 8}                   # newest + both pinned
    # without the fleet dir the same call would have deleted them
    prune_snapshots(str(M), keep=1, fleet_dir="")
    assert set(dict(list_snapshots(str(M)))) == {8}


def test_checkpoint_threads_fleet_dir_pin(tmp_path):
    """Booster.checkpoint must thread serve_fleet_dir into pruning: a
    training run with snapshot_keep=1 keeps the promoted snapshot."""
    from lightgbm_tpu.serving.fleet import promote_pointer

    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "model.txt"
    fleet = tmp_path / "fleet"
    fleet.mkdir()
    p = _binary_params(M, snapshot_freq=2, serve_fleet_dir=str(fleet))
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    snaps = dict(list_snapshots(str(M)))
    assert set(snaps) == {2, 4}
    promote_pointer(str(fleet), snaps[2])
    bst.checkpoint(str(M), keep=1)             # prunes, but pin survives
    assert set(dict(list_snapshots(str(M)))) == {2, 4}


def test_chaos_truncate_snapshot_skipped_by_latest_valid(tmp_path,
                                                         monkeypatch):
    X, y = make_synthetic_binary(n=800)
    M = tmp_path / "model.txt"
    params = _binary_params(M, snapshot_freq=4)
    monkeypatch.setenv(chaos.ENV_VAR, "truncate_snapshot:iter=8")
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    monkeypatch.delenv(chaos.ENV_VAR)
    snaps = dict(list_snapshots(str(M)))
    assert set(snaps) == {4, 8}
    with pytest.raises(LightGBMError):
        validate_checkpoint(snaps[8])          # chaos corrupted it
    assert latest_valid_snapshot(str(M)) == snaps[4]


# ---------------------------------------------------------------------------
# kill / resume through the real process boundary (slow tier)
# ---------------------------------------------------------------------------

def _write_csv(tmp_path, n=900):
    rs = np.random.RandomState(3)
    X = rs.randn(n, 6)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    path = tmp_path / "train.csv"
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6g")
    return path


def _clean_env():
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "LGBTPU_CHAOS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
def test_cli_kill_then_resume_bit_identity(tmp_path):
    """A CLI run killed by the chaos harness at iteration 9 leaves valid
    snapshots; resuming from iteration 5 reproduces the uninterrupted
    model byte-for-byte (params block included)."""
    csv = _write_csv(tmp_path)
    M = tmp_path / "model.txt"
    params = _binary_params(M, snapshot_freq=5)
    full = lgb.train(params, lgb.Dataset(str(csv)), num_boost_round=12)

    env = _clean_env()
    env["LGBTPU_CHAOS"] = "kill:iter=9"
    cli = [sys.executable, "-m", "lightgbm_tpu", f"data={csv}",
           "objective=binary", "num_leaves=15", "min_data_in_leaf=5",
           "verbosity=-1", "num_iterations=12", "snapshot_freq=5",
           f"output_model={M}"]
    out = subprocess.run(cli, env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 137, out.stdout + out.stderr
    assert not M.exists()                      # killed before the final save
    snap = str(M) + ".snapshot_iter_5"
    validate_checkpoint(snap)

    resumed = lgb.train(params, lgb.Dataset(str(csv)), num_boost_round=12,
                        resume_from=snap)
    assert resumed.model_to_string() == full.model_to_string()


@pytest.mark.slow
def test_dist_failfast_on_worker_crash(tmp_path, monkeypatch,
                                       require_two_process_collectives):
    """Regression for the sequential rank-order await: a crashed rank 1
    must fail the run immediately, not after rank 0's full timeout."""
    csv = _write_csv(tmp_path, n=1200)
    monkeypatch.setenv(chaos.ENV_VAR, "kill:iter=2,rank=1")
    t0 = time.time()
    with pytest.raises(LightGBMError, match=r"worker 1/2 failed"):
        lgb.train_distributed({"objective": "binary", "verbosity": -1},
                              str(csv), num_boost_round=200,
                              num_processes=2, timeout=900)
    assert time.time() - t0 < 300   # far under the 900 s attempt timeout


@pytest.mark.slow
def test_dist_kill_retry_resume_bit_identity(
        tmp_path, monkeypatch, require_two_process_collectives):
    csv = _write_csv(tmp_path, n=1200)
    params = {"objective": "binary", "verbosity": -1}
    clean = lgb.train_distributed(dict(params), str(csv), num_boost_round=6,
                                  num_processes=2)
    ref = clean.model_to_string().split("\nparameters:")[0]

    marker = tmp_path / "kill.marker"
    monkeypatch.setenv(chaos.ENV_VAR, f"kill:iter=4,rank=1,once={marker}")
    bst = lgb.train_distributed(
        dict(params, dist_retries=2, dist_backoff=0.2, snapshot_freq=2),
        str(csv), num_boost_round=6, num_processes=2, timeout=900)
    assert marker.exists()                     # the kill really fired
    got = bst.model_to_string().split("\nparameters:")[0]
    assert got == ref


@pytest.mark.slow
def test_dist_hang_detector_fires_and_recovers(
        tmp_path, monkeypatch, require_two_process_collectives):
    csv = _write_csv(tmp_path, n=1000)
    marker = tmp_path / "hang.marker"
    monkeypatch.setenv(chaos.ENV_VAR, f"hang:iter=3,rank=1,once={marker}")
    bst = lgb.train_distributed(
        {"objective": "binary", "verbosity": -1, "dist_retries": 1,
         "dist_backoff": 0.1, "snapshot_freq": 2},
        str(csv), num_boost_round=6, num_processes=2, timeout=900,
        hang_timeout=10)
    assert marker.exists()
    assert bst.num_trees() == 6
