"""Sampled-vs-masked bit-identity suite (GOSS/bagging row compaction).

The tentpole claim of the row-compaction path (ops/compact.plan_sample_rows
+ the compacted grow programs in ops/grow.py): dropping the out-of-bag rows
from every histogram pass removes ONLY exact-zero work.  The A/B reference
is ``row_compaction=pad`` — the same per-tree stable partition at the FULL
row count, i.e. the dense-mask algorithm on the partitioned layout — and
compacted trees must be BYTE-IDENTICAL to it on every training layout
(binary, NaN bins, categorical, multiclass-batched lockstep, the 4-way CPU
mesh under both ``hist_comms`` modes) — the same model-string A/B
discipline as the PR-5 comms tests.

``row_compaction=off`` (the legacy natural-row-order dense mask) is held to
quality equivalence, not bytes: on CPU the blocked f32 dot accumulates in a
position-dependent order, so re-ordering rows legally drifts last-ulp
(exactly the serial-vs-mesh caveat documented in test_distributed.py).
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.utils.log import LightGBMError

from conftest import make_synthetic_binary, make_synthetic_multiclass

N_DEV = len(jax.devices())
needs_mesh = pytest.mark.skipif(N_DEV < 4, reason="needs a >=4-device mesh")

# learning_rate 0.5 keeps the GOSS warmup (no sampling for 1/lr iterations,
# goss.hpp) to 2 iterations so the suite actually exercises sampled trees
GOSS = {"data_sample_strategy": "goss", "learning_rate": 0.5}
BAG = {"bagging_fraction": 0.6, "bagging_freq": 1, "bagging_seed": 5}


def _strip_params(model_str: str) -> str:
    """Model text minus the parameters block (row_compaction differs by
    design; every tree byte must still match)."""
    return model_str.split("\nparameters:")[0]


def _train(params, X, y, mode, rounds=8, **ds_kw):
    p = dict(params, verbosity=-1, num_leaves=15, min_data_in_leaf=5,
             row_compaction=mode)
    bst = lgb.train(p, lgb.Dataset(X, label=y, **ds_kw),
                    num_boost_round=rounds)
    return bst


def _assert_compacted_equal(params, X, y, rounds=8, **ds_kw):
    """auto (compacted) vs pad (dense-mask on the partitioned layout) must
    be byte-equal, and auto must have actually engaged compaction."""
    a = _train(params, X, y, "auto", rounds, **ds_kw)
    p = _train(params, X, y, "pad", rounds, **ds_kw)
    assert a.engine._last_compact_rows > 0, "compaction never engaged"
    assert a.engine._last_sampled_rows > 0
    assert _strip_params(a.model_to_string()) == \
        _strip_params(p.model_to_string())
    return a


# ---------------------------------------------------------------------------
# compacted == dense-mask bit-identity (the tentpole A/B)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_goss_compacted_bit_identical_binary_stream():
    X, y = make_synthetic_binary(n=4000)
    _assert_compacted_equal(dict(GOSS, objective="binary",
                                 hist_backend="stream"), X, y)


@pytest.mark.slow
def test_goss_compacted_bit_identical_nan_bins():
    X, y = make_synthetic_binary(n=4000)
    X = X.copy()
    X[::7, 2] = np.nan                        # MissingType::NaN routing
    _assert_compacted_equal(dict(GOSS, objective="binary",
                                 hist_backend="stream"), X, y)


@pytest.mark.slow
def test_goss_compacted_bit_identical_categorical():
    rs = np.random.RandomState(3)
    X, y = make_synthetic_binary(n=4000)
    X = X.copy()
    X[:, 4] = rs.randint(0, 6, len(X))
    _assert_compacted_equal(dict(GOSS, objective="binary",
                                 hist_backend="stream"), X, y,
                            categorical_feature=[4])


@pytest.mark.slow
def test_goss_compacted_bit_identical_multiclass_batched():
    """The widened K-class lockstep program compacts once per iteration
    (the mask row is shared across classes) and must stay byte-equal."""
    X, y = make_synthetic_multiclass(n=4000, k=3)
    a = _assert_compacted_equal(
        dict(GOSS, objective="multiclass", num_class=3,
             hist_backend="stream"), X, y, rounds=6)
    assert a.engine._mc_batched_last


def test_bagging_compacted_bit_identical_stream():
    X, y = make_synthetic_binary(n=4000)
    _assert_compacted_equal(dict(BAG, objective="binary",
                                 hist_backend="stream"), X, y)


@pytest.mark.slow
def test_pad_mode_unaligned_row_count():
    """n=4500 Dataset-pads to 4608 — NOT a multiple of the stream kernel
    block (1024): pad mode must round its full-row capacity up to the
    block instead of handing the grower an unaligned count (regression:
    ValueError mid-training for ~3 of 4 dataset sizes)."""
    X, y = make_synthetic_binary(n=4500)
    _assert_compacted_equal(dict(GOSS, objective="binary",
                                 hist_backend="stream"), X, y)


@pytest.mark.slow
def test_goss_compacted_bit_identical_segsum():
    """Contraction/segsum backend (the CPU default): per-tree partition
    plan + O(sampled) histogram builds, same byte-equality contract."""
    X, y = make_synthetic_binary(n=4000)
    _assert_compacted_equal(dict(GOSS, objective="binary",
                                 hist_backend="segsum"), X, y)


@needs_mesh
@pytest.mark.parametrize("comms", ["psum", "reduce_scatter"])
@pytest.mark.slow
def test_goss_compacted_bit_identical_mesh_4dev(comms, monkeypatch):
    """4-way data-parallel mesh: every device stable-partitions its OWN
    row shard to the same static capacity (the capacity covers the
    fullest shard), under both histogram collectives.  The GOSS
    threshold itself is a global sort statistic, so the sampled set is
    shard-layout-independent.  256-row kernel blocks keep the per-shard
    slice several blocks deep at test scale (compaction only engages
    when it can actually drop whole blocks)."""
    monkeypatch.setenv("LGBTPU_BLOCK_ROWS", "256")
    X, y = make_synthetic_binary(n=4000)
    p = dict(GOSS, objective="binary", hist_backend="stream",
             tree_learner="data", mesh_shape="data:4", hist_comms=comms)
    a = _assert_compacted_equal(p, X, y)
    assert a.engine._mesh_stream
    assert a.engine._grow_params.hist_comms == comms


@needs_mesh
@pytest.mark.slow
def test_bagging_compacted_bit_identical_mesh_4dev(monkeypatch):
    monkeypatch.setenv("LGBTPU_BLOCK_ROWS", "256")
    X, y = make_synthetic_binary(n=4000)
    p = dict(BAG, objective="binary", hist_backend="stream",
             tree_learner="data", mesh_shape="data:4")
    # bagging_fraction 0.6 sits under the 75% engagement threshold
    _assert_compacted_equal(p, X, y)


# ---------------------------------------------------------------------------
# compacted vs legacy natural-order dense mask: quality equivalence
# ---------------------------------------------------------------------------

def test_goss_compacted_matches_legacy_quality():
    X, y = make_synthetic_binary(n=4000)
    params = dict(GOSS, objective="binary", hist_backend="stream")
    a = _train(params, X, y, "auto", rounds=10)
    o = _train(params, X, y, "off", rounds=10)
    assert o.engine._last_compact_rows == 0
    pa = np.asarray(a.predict(X))
    po = np.asarray(o.predict(X))
    # same algorithm, row order aside: predictions agree to f32 noise
    np.testing.assert_allclose(pa, po, rtol=2e-3, atol=2e-3)
    acc_a = np.mean((pa > 0.5) == y)
    acc_o = np.mean((po > 0.5) == y)
    assert abs(acc_a - acc_o) < 0.02
    assert acc_a > 0.7


def test_compaction_skips_when_not_worth_it():
    """A 0.9 bagging fraction saves <25% of rows — the engine must stay on
    the dense path rather than pay the partition + route-only overhead."""
    X, y = make_synthetic_binary(n=3000)
    p = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
         "hist_backend": "stream", "bagging_fraction": 0.9,
         "bagging_freq": 1}
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    assert bst.engine._last_compact_rows == 0
    assert bst.engine._last_sampled_rows > 0     # telemetry still counted


def test_env_override_forces_mode():
    X, y = make_synthetic_binary(n=3000)
    params = dict(GOSS, objective="binary", hist_backend="stream",
                  verbosity=-1, num_leaves=15)
    os.environ["LGBTPU_COMPACT"] = "off"
    try:
        bst = lgb.train(dict(params, row_compaction="auto"),
                        lgb.Dataset(X, label=y), num_boost_round=4)
        assert bst.engine._last_compact_rows == 0
    finally:
        del os.environ["LGBTPU_COMPACT"]


# ---------------------------------------------------------------------------
# checkpoint/resume + rollback: sampling RNG position is the iteration
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_resume_bit_identity_goss_compacted(tmp_path):
    """Resume mid-run with GOSS sampling + compaction active: the
    strategy's RNG stream position is derived from the iteration counter
    the snapshot stores, so the continued run is byte-identical."""
    X, y = make_synthetic_binary(n=3000)
    M = tmp_path / "goss.txt"
    params = dict(GOSS, objective="binary", hist_backend="stream",
                  num_leaves=15, min_data_in_leaf=5, verbosity=-1,
                  snapshot_freq=4, output_model=str(M))
    full = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    assert full.engine._last_compact_rows > 0    # sampled trees were grown
    resumed = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                        resume_from=str(M) + ".snapshot_iter_4")
    assert resumed.model_to_string() == full.model_to_string()


def test_resume_bit_identity_bagging_midepoch_compacted(tmp_path):
    """bagging_freq=2 with a snapshot INSIDE a bagging epoch (iter 3):
    the resumed run must regenerate the epoch's cached mask, not draw a
    fresh one — the iteration-keyed cache fix."""
    X, y = make_synthetic_binary(n=3000)
    M = tmp_path / "bag.txt"
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
              "verbosity": -1, "bagging_fraction": 0.6, "bagging_freq": 2,
              "hist_backend": "stream", "snapshot_freq": 3,
              "output_model": str(M)}
    full = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    assert full.engine._last_compact_rows > 0
    resumed = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                        resume_from=str(M) + ".snapshot_iter_3")
    assert resumed.model_to_string() == full.model_to_string()


def test_bagging_mask_cache_iteration_keyed():
    """Regression for the `_mask_iter` staleness bug: with bagging_freq>1
    the cache used to refresh only on `iteration % freq == 0`, so visiting
    iterations out of order (rollback_one_iter) reused a LATER epoch's
    mask.  The cache is now keyed on the bagging epoch."""
    from lightgbm_tpu.models.sample_strategy import BaggingSampleStrategy
    cfg = Config.from_params({"bagging_fraction": 0.5, "bagging_freq": 2,
                              "bagging_seed": 7})
    g = jnp.ones(512)
    h = jnp.ones(512)
    s = BaggingSampleStrategy(cfg, 512)
    m4 = np.asarray(s.sample(4, g, h)[0])        # epoch 2
    m3 = np.asarray(s.sample(3, g, h)[0])        # rollback into epoch 1
    fresh = BaggingSampleStrategy(cfg, 512)
    m3_fresh = np.asarray(fresh.sample(3, g, h)[0])
    assert np.array_equal(m3, m3_fresh)
    assert not np.array_equal(m4, m3)            # epochs genuinely differ


# ---------------------------------------------------------------------------
# config validation (reference: Config::CheckParamConflict)
# ---------------------------------------------------------------------------

def test_goss_rate_sum_rejected():
    with pytest.raises(LightGBMError, match=r"top_rate \+ other_rate"):
        Config.from_params({"data_sample_strategy": "goss",
                            "top_rate": 0.9, "other_rate": 0.2})


def test_goss_negative_rate_rejected():
    with pytest.raises(LightGBMError, match="non-negative"):
        Config.from_params({"boosting": "goss", "top_rate": -0.1})


def test_goss_with_bagging_rejected():
    with pytest.raises(LightGBMError, match="bagging"):
        Config.from_params({"data_sample_strategy": "goss",
                            "bagging_freq": 1, "bagging_fraction": 0.5})


def test_row_compaction_value_validated():
    with pytest.raises(LightGBMError, match="row_compaction"):
        Config.from_params({"row_compaction": "sometimes"})


def test_goss_without_bagging_accepted():
    cfg = Config.from_params({"data_sample_strategy": "goss",
                              "top_rate": 0.3, "other_rate": 0.2})
    assert cfg.top_rate == 0.3


def test_goss_with_posneg_bagging_rejected():
    """Balanced bagging (pos/neg fractions < 1) is active bagging too —
    GOSS must reject it, not silently drop the balancing request."""
    with pytest.raises(LightGBMError, match="bagging"):
        Config.from_params({"data_sample_strategy": "goss",
                            "bagging_freq": 1, "bagging_fraction": 1.0,
                            "pos_bagging_fraction": 0.5})


def test_rollback_invalidates_count_cache():
    """rollback_one_iter only f32-approximately restores the score, so a
    re-run of the iteration may draw a different GOSS mask under the same
    mask_key — the cached in-bag counts must be dropped (a stale
    undersized capacity would silently truncate in-bag rows)."""
    X, y = make_synthetic_binary(n=3000)
    p = dict(GOSS, objective="binary", hist_backend="stream",
             verbosity=-1, num_leaves=15)
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
    eng = bst.engine
    assert eng._sample_count_cache is not None
    eng.rollback_one_iter()
    assert eng._sample_count_cache is None
    assert not eng.train_one_iter()          # retrains cleanly
    assert eng._sample_count_cache is not None


def test_goss_warmup_counts_cached_once():
    """All warmup iterations share one all-ones mask — mask_key returns a
    constant during warmup so the engine syncs the count once, not per
    iteration."""
    from lightgbm_tpu.models.sample_strategy import GOSSStrategy
    cfg = Config.from_params({"data_sample_strategy": "goss",
                              "learning_rate": 0.1})
    s = GOSSStrategy(cfg, 100)
    assert s.mask_key(0) == s.mask_key(9) == -1     # 1/lr = 10 warmup iters
    assert s.mask_key(10) == 10
    assert s.mask_key(11) != s.mask_key(12)


def test_goss_strategy_selection_case_insensitive():
    """Config validation matches 'GOSS' case-insensitively — the strategy
    factory must agree, or a non-lowercase spelling is blocked from
    bagging params while silently never running GOSS."""
    from lightgbm_tpu.models.sample_strategy import (GOSSStrategy,
                                                     create_sample_strategy)
    cfg = Config.from_params({"data_sample_strategy": "GOSS"})
    assert isinstance(create_sample_strategy(cfg, 100), GOSSStrategy)


def test_goss_with_inactive_bagging_accepted():
    """bagging_fraction=1.0 leaves bagging a no-op — the reference's
    CheckParamConflict only fires on an ACTIVE bagging config, so this
    param set must keep constructing (compatibility with existing
    configs that carry a vestigial bagging_freq)."""
    cfg = Config.from_params({"data_sample_strategy": "goss",
                              "bagging_freq": 5, "bagging_fraction": 1.0})
    assert cfg.bagging_freq == 5


def test_env_override_typo_rejected():
    """An LGBTPU_COMPACT typo bypasses Config validation — it must raise
    at train time, not silently run as 'auto'."""
    X, y = make_synthetic_binary(n=3000)
    params = dict(GOSS, objective="binary", hist_backend="stream",
                  verbosity=-1, num_leaves=15)
    os.environ["LGBTPU_COMPACT"] = "bogus"
    try:
        with pytest.raises(LightGBMError, match="LGBTPU_COMPACT"):
            lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    finally:
        del os.environ["LGBTPU_COMPACT"]


# ---------------------------------------------------------------------------
# telemetry: per-iteration sampled_rows
# ---------------------------------------------------------------------------

def test_sampled_rows_telemetry_field():
    from lightgbm_tpu.telemetry import global_registry
    global_registry.reset()
    X, y = make_synthetic_binary(n=3000)
    p = dict(GOSS, objective="binary", hist_backend="stream",
             num_leaves=15, verbosity=-1, telemetry=True)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=6)
    recs = [r for r in global_registry.records
            if r.get("event") == "iteration"]
    assert recs, "no iteration records"
    last = recs[-1]
    assert 0 < last["sampled_rows"] < len(X)
    assert last["compact_rows"] > 0
    # warmup iterations (no sampling yet) report the full row count
    assert recs[0]["sampled_rows"] >= last["sampled_rows"]
