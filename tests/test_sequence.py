"""Streaming Sequence ingestion (reference: python-package basic.py:841
Sequence ABC + two-round sampling / DatasetPushRows streaming construction)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


class _ArraySeq(lgb.Sequence):
    batch_size = 97          # deliberately odd to exercise batching

    def __init__(self, arr):
        self._a = arr

    def __getitem__(self, idx):
        return self._a[idx]

    def __len__(self):
        return len(self._a)


def _data(n=1500, f=6, seed=4):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    X[::11, 2] = np.nan
    y = X[:, 0] + np.sin(2 * X[:, 1]) + 0.1 * rs.randn(n)
    return X, y


def test_sequence_binning_matches_dense():
    X, y = _data()
    ds_seq = lgb.Dataset(_ArraySeq(X), label=y)
    ds_dense = lgb.Dataset(X, label=y)
    ds_seq.construct()
    ds_dense.construct()
    np.testing.assert_array_equal(np.asarray(ds_seq.binned.bins),
                                  np.asarray(ds_dense.binned.bins))
    assert ds_seq.binned.group_features == ds_dense.binned.group_features


def test_sequence_multiple_chunks_train():
    X, y = _data(n=2000)
    seqs = [_ArraySeq(X[:700]), _ArraySeq(X[700:1200]), _ArraySeq(X[1200:])]
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    bst_seq = lgb.train(params, lgb.Dataset(seqs, label=y),
                        num_boost_round=5)
    bst_dense = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    np.testing.assert_allclose(bst_seq.predict(X), bst_dense.predict(X),
                               rtol=1e-6, atol=1e-7)
