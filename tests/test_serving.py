"""Online inference serving (docs/SERVING.md).

The serving contract under test:

  * the shape-bucketed compiled predictor is BITWISE identical to
    ``Booster.predict`` (raw and transformed, binary and multiclass,
    categorical + NaN + zero-as-missing rows), at every batch size;
  * bucket padding and micro-batch coalescing never change outputs;
  * hot-reload is atomic: under concurrent traffic zero requests drop
    and every response matches the exact model version it reports;
  * overload rejects with a structured payload instead of buffering;
  * the loopback end-to-end flow sustains concurrent mixed-size traffic
    with ZERO XLA recompiles after warmup (telemetry watchdog counters)
    and survives a mid-traffic ``/reload``.
"""
import http.client
import json
import os
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (MicroBatcher, ModelRegistry, OverloadError,
                                  ServingApp, bucket_ladder)
from lightgbm_tpu.telemetry import recompile_counts


def _make_data(seed=7, n=800):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    X[:, 4] = rs.randint(0, 9, n)
    X[rs.rand(n) < 0.15, 0] = np.nan
    y = ((X[:, 1] > 0) ^ (X[:, 4] == 3)).astype(np.float64)
    return X, y


def _train_to_file(path, seed=3, num_boost_round=8, objective="binary",
                   num_class=1):
    X, y = _make_data()
    if objective != "binary":
        rs = np.random.RandomState(seed)
        y = rs.randint(0, num_class, len(y)).astype(np.float64)
    params = {"objective": objective, "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "seed": seed}
    if num_class > 1:
        params["num_class"] = num_class
    ds = lgb.Dataset(X, label=y, categorical_feature=[4])
    bst = lgb.train(params, ds, num_boost_round=num_boost_round)
    bst.save_model(str(path))
    return X


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """(model_path_a, model_path_b, X, ref_a, ref_b) — two models of the
    same shape plus reference boosters loaded from file."""
    td = tmp_path_factory.mktemp("serving")
    pa, pb = td / "model_a.txt", td / "model_b.txt"
    X = _train_to_file(pa, seed=3)
    _train_to_file(pb, seed=11)
    return (str(pa), str(pb), X,
            lgb.Booster(model_file=str(pa)), lgb.Booster(model_file=str(pb)))


# ---------------------------------------------------------------------------
# compiled predictor
# ---------------------------------------------------------------------------

def test_bucket_ladder():
    assert bucket_ladder(256) == [8, 16, 32, 64, 128, 256]
    assert bucket_ladder(100) == [8, 16, 32, 64, 128]
    assert bucket_ladder(4) == [8]
    assert bucket_ladder(999, "8,64,256") == [8, 64, 256]
    with pytest.raises(lgb.LightGBMError):
        bucket_ladder(256, "0,8")
    with pytest.raises(lgb.LightGBMError, match="integers"):
        bucket_ladder(256, "8,x")


@pytest.mark.parametrize("raw", [True, False])
def test_compiled_bit_identical_to_predict(served, raw):
    pa, _, X, ref, _ = served
    model = ModelRegistry(pa, max_batch=64).current()
    for sz in (1, 2, 3, 7, 8, 9, 31, 64, 65, 200, 800):
        got = model.predict(X[:sz], raw_score=raw)
        want = ref.predict(X[:sz], raw_score=raw)
        assert got.shape == want.shape
        assert np.array_equal(got, want), \
            f"size {sz}: max |diff| {np.abs(got - want).max()}"


def test_compiled_multiclass_bit_identical(tmp_path):
    mp = tmp_path / "mc.txt"
    X = _train_to_file(mp, objective="multiclass", num_class=3)
    ref = lgb.Booster(model_file=str(mp))
    model = ModelRegistry(str(mp), max_batch=32).current()
    for sz in (1, 5, 33, 200):
        for raw in (True, False):
            assert np.array_equal(model.predict(X[:sz], raw_score=raw),
                                  ref.predict(X[:sz], raw_score=raw))


def test_bucket_padding_never_changes_outputs(served):
    pa, _, X, ref, _ = served
    model = ModelRegistry(pa, max_batch=64).current()
    full = model.predict(X[:200], raw_score=True)
    # every sub-span lands in different buckets/padding, same values
    for s, e in ((0, 5), (3, 20), (7, 71), (100, 200), (5, 6)):
        assert np.array_equal(model.predict(X[s:e], raw_score=True),
                              full[s:e])


def test_device_vs_host_accumulation_bitwise(served, monkeypatch):
    """The on-device f64 leaf accumulation and the host-loop fallback
    (LGBTPU_SERVE_ACCUM=host) are the same bits — and both equal
    Booster.predict."""
    pa, _, X, ref, _ = served
    from lightgbm_tpu.serving.compiled import CompiledPredictor
    trees = ref._all_trees()
    dev = CompiledPredictor(trees, 1, X.shape[1], max_batch=64)
    assert dev.device_accum, "CPU backend must support device f64"
    monkeypatch.setenv("LGBTPU_SERVE_ACCUM", "host")
    host = CompiledPredictor(trees, 1, X.shape[1], max_batch=64)
    assert not host.device_accum
    want = np.zeros(200, np.float64)
    for t in trees:
        want += t.predict_raw(X[:200])
    for got in (dev.raw_scores(X[:200]), host.raw_scores(X[:200])):
        assert np.array_equal(got, want)
    # leaves() introspection surface agrees with the scored walk
    lv = dev.leaves(X[:50])
    assert lv.shape == (len(trees), 50)
    acc = np.zeros(50, np.float64)
    for i, t in enumerate(trees):
        acc += np.asarray(t.leaf_value, np.float64)[lv[i]]
    assert np.array_equal(acc, want[:50])


def test_serve_accum_env_validation(monkeypatch):
    from lightgbm_tpu.serving.compiled import device_accumulation_supported
    monkeypatch.setenv("LGBTPU_SERVE_ACCUM", "sideways")
    with pytest.raises(lgb.LightGBMError, match="LGBTPU_SERVE_ACCUM"):
        device_accumulation_supported()


def test_categorical_bitset_edges_bitwise(served, tmp_path):
    """The device categorical walk's word-index edges, mirroring the
    native UBSan fixture (tests/test_native_sanitizers.py): bits 31 / 32
    / 63 of a two-word bitset, the first word index past the span (64),
    far-out-of-range (1e12, 2^31 + epsilon), negative, fractional, and
    NaN values — every one bitwise equal to Booster.predict."""
    rs = np.random.RandomState(6)
    X = 0.01 * rs.randn(900, 6)
    X[:, 4] = rs.randint(0, 6, 900)
    yc = 3.0 * np.isin(X[:, 4], [1, 4]).astype(float) \
        + 0.01 * rs.randn(900)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "max_cat_to_onehot": 1},
                    lgb.Dataset(X, label=yc, categorical_feature=[4]),
                    num_boost_round=4)
    trees = bst._all_trees()
    patched = 0
    for t in trees:
        ni = max(t.num_leaves - 1, 0)
        cat_nodes = np.nonzero(
            (np.asarray(t.decision_type[:ni]).astype(np.int64) & 1) > 0)[0]
        if len(cat_nodes) == 0:
            continue
        # every cat node gets a fresh TWO-WORD bitset holding exactly
        # bits {31, 32, 63} (word 0 bit 31; word 1 bits 0 and 31)
        bounds = [0]
        words = []
        for k, i in enumerate(cat_nodes):
            t.threshold_bin[i] = k
            t.threshold[i] = float(k)
            words.extend([np.uint32(1 << 31), np.uint32(1 | (1 << 31))])
            bounds.append(bounds[-1] + 2)
        t.cat_boundaries = np.asarray(bounds, np.int32)
        t.cat_threshold = np.asarray(words, np.uint32)
        patched += len(cat_nodes)
    assert patched > 0, "model should contain categorical splits"
    mp = tmp_path / "edges.txt"
    bst.save_model(str(mp))

    ref = lgb.Booster(model_file=str(mp))
    model = ModelRegistry(str(mp), max_batch=64).current()
    edge_vals = [31.0, 32.0, 63.0, 30.0, 33.0, 64.0, 95.0, 1e12,
                 -3.0, -0.5, 2.5, 31.9, float(2 ** 31) + 7.0,
                 float(np.nan), 0.0]
    Xt = np.repeat(X[:1], len(edge_vals), axis=0)
    Xt[:, 4] = edge_vals
    for sz in (1, len(edge_vals)):
        got = model.predict(Xt[:sz], raw_score=True)
        want = ref.predict(Xt[:sz], raw_score=True)
        assert np.array_equal(got, want), \
            f"size {sz}: |diff| {np.abs(got - want).max()}"
    # the crafted bitset has routing power: in-set (31/32/63) and
    # out-of-set (30/64/huge) values land on different scores
    full = ref.predict(Xt, raw_score=True)
    assert not np.allclose(full[0], full[5])


def test_zero_rows_and_feature_mismatch(served):
    pa, _, X, _, _ = served
    model = ModelRegistry(pa).current()
    assert model.predict(np.zeros((0, 6))).shape == (0,)
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        model.predict(X[:3, :4])


# ---------------------------------------------------------------------------
# registry: validation + hot reload
# ---------------------------------------------------------------------------

def test_registry_rejects_truncated_model(served, tmp_path):
    pa, _, _, _, _ = served
    reg = ModelRegistry(pa)
    v1 = reg.version
    bad = tmp_path / "trunc.txt"
    text = open(pa).read()
    bad.write_text(text[:len(text) // 2])
    with pytest.raises(lgb.LightGBMError, match="truncated"):
        reg.load(str(bad))
    # the old model keeps serving, version unchanged
    assert reg.version == v1
    assert reg.current().path == pa
    assert reg.reloads_failed == 1


def test_registry_manifest_sha256(served, tmp_path):
    pa, _, X, ref, _ = served
    data = open(pa, "rb").read()
    good = tmp_path / "m.txt"
    good.write_bytes(data)
    import hashlib
    manifest = {"model_sha256": hashlib.sha256(data).hexdigest()}
    (tmp_path / "m.txt.manifest.json").write_text(json.dumps(manifest))
    reg = ModelRegistry(str(good))       # valid manifest: loads
    assert np.array_equal(reg.current().predict(X[:5]), ref.predict(X[:5]))
    # now corrupt the payload under the sealed manifest
    good.write_bytes(data + b"# tail\n")
    with pytest.raises(lgb.LightGBMError, match="sha256"):
        reg.load(str(good))
    assert reg.current().sha256 == manifest["model_sha256"]


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------

def test_batcher_coalescing_bit_identical(served):
    pa, _, X, ref, _ = served
    reg = ModelRegistry(pa, max_batch=64)
    b = MicroBatcher(reg, max_batch=64, max_delay_ms=25.0,
                     queue_size=256).start()
    try:
        sizes = [1, 3, 1, 7, 2, 12, 1, 5, 9, 1, 4, 6]
        offs = np.cumsum([0] + sizes)
        futs = [b.submit(X[offs[i]:offs[i + 1]]) for i in range(len(sizes))]
        results = [f.result(timeout=10) for f in futs]
        for i, res in enumerate(results):
            want = ref.predict(X[offs[i]:offs[i + 1]])
            assert np.array_equal(res.values, want)
        # the delay window actually coalesced somebody
        assert any(r.batched_rows > sizes[i]
                   for i, r in enumerate(results))
        assert b.served == len(sizes)
    finally:
        b.stop()


def test_batcher_singleton_fast_path(served):
    pa, _, X, ref, _ = served
    reg = ModelRegistry(pa)
    b = MicroBatcher(reg).start()
    try:
        res = b.submit(X[0], fast=True).result(timeout=5)
        assert np.array_equal(res.values, ref.predict(X[:1]))
        assert res.batched_rows == 1
    finally:
        b.stop()


def test_batcher_overload_structured_rejection(served):
    pa, _, X, _, _ = served
    reg = ModelRegistry(pa)
    b = MicroBatcher(reg, queue_size=2, max_delay_ms=1.0)   # worker NOT started
    f1 = b.submit(X[:2])
    f2 = b.submit(X[:2])
    with pytest.raises(OverloadError) as ei:
        b.submit(X[:2])
    payload = ei.value.payload()
    assert payload["error"] == "overload"
    assert payload["queue_size"] == 2
    assert payload["queue_depth"] == 2
    assert b.rejected == 1
    # admitted requests still complete once the worker runs (drain)
    b.start()
    assert f1.result(timeout=10) is not None
    assert f2.result(timeout=10) is not None
    b.stop()


def test_batcher_stop_drains_queue(served):
    pa, _, X, _, _ = served
    reg = ModelRegistry(pa)
    b = MicroBatcher(reg, max_delay_ms=1.0)    # worker not started yet
    futs = [b.submit(X[i:i + 2]) for i in range(6)]
    b.start()
    b.stop(drain=True)
    assert all(f.done() and f.exception() is None for f in futs)


def test_hot_reload_under_concurrent_traffic(served):
    """The swap drains by reference: zero dropped futures, and every
    response is bitwise consistent with the version it reports."""
    pa, pb, X, ref_a, ref_b = served
    expected = {}   # version -> full-prediction oracle
    reg = ModelRegistry(pa, max_batch=32)
    expected[reg.version] = ref_a.predict(X[:420], raw_score=True)
    b = MicroBatcher(reg, max_batch=32, max_delay_ms=1.0,
                     queue_size=512).start()
    stop = threading.Event()
    out, errs = [], []

    def client(seed):
        rs = np.random.RandomState(seed)
        while not stop.is_set():
            s = rs.randint(0, 390)
            m = int(rs.choice([1, 2, 5, 9]))
            try:
                f = b.submit(X[s:s + m], raw_score=True)
                out.append((s, m, f.result(timeout=10)))
            except OverloadError:
                pass
            except Exception as e:  # noqa: BLE001 — collected for assert
                errs.append(e)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    try:
        for path, oracle in ((pb, ref_b), (pa, ref_a)):
            time.sleep(0.15)
            model = reg.load(path)    # mid-traffic swap
            expected[model.version] = oracle.predict(X[:420], raw_score=True)
        time.sleep(0.15)
    finally:
        stop.set()
        for t in threads:
            t.join(10)
        b.stop()
    assert not errs, errs[:3]
    assert len(out) > 20
    seen_versions = {res.model_version for _, _, res in out}
    assert len(seen_versions) >= 2          # traffic spanned the swap
    for s, m, res in out:
        want = expected[res.model_version][s:s + m]
        assert np.array_equal(res.values, want), \
            f"rows {s}:{s+m} mis-scored for v{res.model_version}"


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

def _post(host, port, path, obj, timeout=15):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(obj),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def _get(host, port, path, timeout=15):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def test_server_end_to_end_loopback(served, tmp_path):
    """Acceptance: concurrent mixed-size requests sustain with zero XLA
    recompiles after warmup, and a mid-traffic /reload completes with
    zero dropped or mis-versioned responses."""
    pa, pb, X, ref_a, ref_b = served
    hb = tmp_path / "serve.heartbeat"
    app = ServingApp(pa, port=0, max_batch=32, max_delay_ms=1.0,
                     queue_size=512, heartbeat_path=str(hb)).start()
    host, port = app.host, app.port
    expected = {app.registry.version: ref_a.predict(X[:420], raw_score=True)}
    try:
        # ---- warmup traffic covers the whole ladder, then pin compiles
        for m in (1, 5, 17, 32):
            st, _ = _post(host, port, "/predict",
                          {"rows": X[:m].tolist(), "raw_score": True})
            assert st == 200
        compiles_before = dict(recompile_counts())

        stop = threading.Event()
        responses, errs = [], []

        def client(seed):
            rs = np.random.RandomState(seed)
            while not stop.is_set():
                s = rs.randint(0, 390)
                m = int(rs.choice([1, 2, 7, 16, 29]))
                try:
                    st, obj = _post(host, port, "/predict",
                                    {"rows": X[s:s + m].tolist(),
                                     "raw_score": True})
                    responses.append((s, m, st, obj))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.25)
        # ---- steady concurrent mixed-size traffic re-traced NOTHING
        assert recompile_counts().get("serve_predict") == \
            compiles_before.get("serve_predict"), "recompiles mid-traffic"
        # ---- mid-traffic hot swap; the candidate warms its own buckets
        # BEFORE the version swap, so any fresh traces land here, not in
        # the serving phases
        st, obj = _post(host, port, "/reload", {"path": pb})
        assert st == 200, obj
        expected[obj["model_version"]] = ref_b.predict(X[:420],
                                                       raw_score=True)
        compiles_post_reload = dict(recompile_counts())
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join(10)
        assert not errs, errs[:3]
        assert len(responses) > 20
        versions = set()
        for s, m, st, obj in responses:
            assert st == 200, obj            # zero dropped / zero overload
            v = obj["model_version"]
            versions.add(v)
            want = expected[v][s:s + m]
            assert np.array_equal(np.asarray(obj["predictions"]), want), \
                f"rows {s}:{s+m} mis-versioned response (v{v})"
        assert len(versions) >= 2            # traffic spanned the swap
        # ---- post-reload steady traffic re-traced nothing either
        compiles_after = dict(recompile_counts())
        assert compiles_after.get("serve_predict") == \
            compiles_post_reload.get("serve_predict"), \
            f"recompiles after swap: {compiles_post_reload} -> {compiles_after}"

        # ---- observability endpoints
        st, h = _get(host, port, "/health")
        assert st == 200 and h["status"] == "ok" and h["worker_alive"]
        assert "heartbeat_age_s" in h       # worker beat the liveness file
        st, stats = _get(host, port, "/stats")
        assert st == 200
        assert stats["served"] >= len(responses)
        assert stats["rejected"] == 0
        assert stats["registry"]["model"]["version"] == max(versions)
        # ---- error surfaces
        st, obj = _post(host, port, "/predict", {"rows": [[1.0, 2.0]]})
        assert st == 400 and "features" in obj["error"]
        st, obj = _post(host, port, "/predict", {})
        assert st == 400
        # ragged / non-numeric payloads are client errors, not 500s
        st, obj = _post(host, port, "/predict", {"rows": [[1, 2], [3]]})
        assert st == 400 and "numeric" in obj["error"]
        st, obj = _post(host, port, "/predict", {"rows": [["a"] * 6]})
        assert st == 400
        st, obj = _post(host, port, "/reload", {"path": pa + ".nope"})
        assert st == 409
        st, obj = _get(host, port, "/nope")
        assert st == 404
    finally:
        app.shutdown(drain=True)
    assert not app.batcher.worker_alive


def test_server_stats_percentiles_with_telemetry(served):
    from lightgbm_tpu import telemetry
    pa, _, X, _, _ = served
    telemetry.reset()
    telemetry.enable()
    try:
        app = ServingApp(pa, port=0, max_batch=16, max_delay_ms=1.0).start()
        try:
            for m in (1, 4, 16, 9, 2):
                st, _ = _post(app.host, app.port, "/predict",
                              {"rows": X[:m].tolist()})
                assert st == 200
            st, stats = _get(app.host, app.port, "/stats")
            assert st == 200
            assert {"p50", "p95", "p99"} <= set(stats["latency"])
            assert stats["latency"]["p50"] <= stats["latency"]["p99"]
            assert {"p50", "p95", "p99"} <= set(stats["batch_rows"])
        finally:
            app.shutdown()
    finally:
        telemetry.disable()
        telemetry.reset()


def test_server_keepalive_consumes_bodies(served):
    """Every POST branch must drain the request body — HTTP/1.1
    keep-alive would otherwise leave body bytes in the stream and desync
    every later request on the same connection."""
    pa, _, X, ref, _ = served
    app = ServingApp(pa, port=0, max_batch=16, max_delay_ms=1.0).start()
    conn = http.client.HTTPConnection(app.host, app.port, timeout=15)
    try:
        # 404 POST with a fat body, then a real predict on the SAME
        # persistent connection
        for path, code in (("/nope", 404), ("/predict", 200)):
            conn.request("POST", path,
                         json.dumps({"rows": X[:4].tolist()}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            obj = json.loads(r.read())
            assert r.status == code, obj
        assert np.array_equal(np.asarray(obj["predictions"]),
                              ref.predict(X[:4]))
    finally:
        conn.close()
        app.shutdown()


def test_cli_serve_requires_model():
    from lightgbm_tpu.serving.server import serve_from_params
    with pytest.raises(lgb.LightGBMError, match="input_model"):
        serve_from_params({"task": "serve"})


def test_serve_module_usage_line():
    import subprocess
    import sys
    r = subprocess.run([sys.executable, "-m", "lightgbm_tpu.serve"],
                       capture_output=True, text=True,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 1
    assert "serve" in (r.stdout + r.stderr)
