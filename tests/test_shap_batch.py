"""Vectorised TreeSHAP equals the scalar reference implementation and is
additive (reference: src/io/tree.cpp TreeSHAP; Lundberg exact algorithm)."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.shap import _tree_shap, predict_contrib


def _model(seed=3, n=400):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    X[:, 4] = rs.randint(0, 5, n)
    X[rs.rand(n) < 0.1, 0] = np.nan
    y = X[:, 1] * 2 + np.nan_to_num(X[:, 0]) + (X[:, 4] == 2) + 0.1 * rs.randn(n)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[4]),
                    num_boost_round=4)
    return bst, X


def test_batch_shap_matches_scalar():
    bst, X = _model()
    trees = bst._all_trees()
    contrib = predict_contrib(trees, X[:40], 1)
    nf = X.shape[1]
    for r in range(0, 40, 7):
        phi = np.zeros(nf + 1)
        for t in trees:
            if t.num_leaves <= 1:
                continue
            _tree_shap(t, X[r], phi, 0, [], 1.0, 1.0, -1)
        np.testing.assert_allclose(contrib[r, :nf], phi[:nf],
                                   rtol=1e-8, atol=1e-10)


def test_shap_additivity():
    bst, X = _model(seed=5)
    contrib = bst.predict(X[:100], pred_contrib=True)
    raw = bst.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6, atol=1e-8)
