"""Vectorised TreeSHAP equals the scalar reference implementation and is
additive (reference: src/io/tree.cpp TreeSHAP; Lundberg exact algorithm)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.shap import _tree_shap, predict_contrib


def _model(seed=3, n=400):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    X[:, 4] = rs.randint(0, 5, n)
    X[rs.rand(n) < 0.1, 0] = np.nan
    y = X[:, 1] * 2 + np.nan_to_num(X[:, 0]) + (X[:, 4] == 2) + 0.1 * rs.randn(n)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[4]),
                    num_boost_round=4)
    return bst, X


@pytest.mark.slow
def test_batch_shap_matches_scalar():
    bst, X = _model()
    trees = bst._all_trees()
    contrib = predict_contrib(trees, X[:40], 1)
    nf = X.shape[1]
    for r in range(0, 40, 7):
        phi = np.zeros(nf + 1)
        for t in trees:
            if t.num_leaves <= 1:
                continue
            _tree_shap(t, X[r], phi, 0, [], 1.0, 1.0, -1)
        np.testing.assert_allclose(contrib[r, :nf], phi[:nf],
                                   rtol=1e-8, atol=1e-10)


def test_shap_additivity():
    bst, X = _model(seed=5)
    contrib = bst.predict(X[:100], pred_contrib=True)
    raw = bst.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6, atol=1e-8)


def test_device_shap_matches_host_walk():
    """The jitted device TreeSHAP must reproduce the exact host walk
    (f32 tolerance; off-boundary test rows)."""
    import os
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import shap as shap_mod
    rs = np.random.RandomState(3)
    X = rs.randn(800, 8)
    y = X[:, 0] + np.sin(2 * X[:, 1]) + 0.1 * rs.randn(800)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=8)
    Xt = rs.randn(300, 8)
    os.environ["LGBTPU_SHAP_DEVICE"] = "1"
    try:
        dev = bst.predict(Xt, pred_contrib=True)
    finally:
        os.environ["LGBTPU_SHAP_DEVICE"] = "0"
        host = bst.predict(Xt, pred_contrib=True)
        del os.environ["LGBTPU_SHAP_DEVICE"]
    np.testing.assert_allclose(dev, host, rtol=2e-4, atol=2e-5)
    # additivity: contributions sum to the raw prediction
    pred = np.asarray(bst.predict(Xt, raw_score=True))
    np.testing.assert_allclose(np.asarray(dev).sum(axis=1), pred,
                               rtol=1e-4, atol=1e-4)
