"""sklearn API tests (model: reference tests/python_package_test/test_sklearn.py)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import (make_synthetic_binary, make_synthetic_multiclass,
                      make_synthetic_regression)


def test_regressor():
    X, y = make_synthetic_regression()
    m = lgb.LGBMRegressor(n_estimators=30, num_leaves=31, verbosity=-1)
    m.fit(X, y)
    assert m.score(X, y) > 0.7
    assert m.n_features_ == X.shape[1]
    assert len(m.feature_importances_) == X.shape[1]


def test_classifier_binary():
    X, y = make_synthetic_binary()
    m = lgb.LGBMClassifier(n_estimators=30, verbosity=-1)
    m.fit(X, y)
    assert set(m.classes_) == {0.0, 1.0}
    proba = m.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
    assert m.score(X, y) > 0.8


def test_classifier_multiclass():
    X, y = make_synthetic_multiclass()
    m = lgb.LGBMClassifier(n_estimators=20, num_leaves=15, verbosity=-1)
    m.fit(X, y)
    assert m.n_classes_ == 4
    assert m.predict_proba(X).shape == (len(y), 4)
    assert m.score(X, y) > 0.8


def test_classifier_string_labels():
    X, y = make_synthetic_binary()
    ys = np.where(y > 0, "pos", "neg")
    m = lgb.LGBMClassifier(n_estimators=15, verbosity=-1)
    m.fit(X, ys)
    pred = m.predict(X)
    assert set(np.unique(pred)) <= {"pos", "neg"}
    assert np.mean(pred == ys) > 0.8


@pytest.mark.slow
def test_eval_set_and_early_stopping():
    X, y = make_synthetic_regression(n=3000)
    rs = np.random.RandomState(5)
    test = rs.rand(len(y)) < 0.3
    m = lgb.LGBMRegressor(n_estimators=300, verbosity=-1,
                          early_stopping_round=5)
    m.fit(X[~test], y[~test], eval_set=[(X[test], y[test])])
    assert m.best_iteration_ > 0
    assert "valid_0" in m.evals_result_


def test_custom_objective_sklearn():
    X, y = make_synthetic_regression()

    def custom_l2(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_true)

    m = lgb.LGBMRegressor(n_estimators=20, objective=custom_l2, verbosity=-1)
    m.fit(X, y)
    pred = m.predict(X, raw_score=True)
    assert np.mean((pred - y) ** 2) < 0.6 * np.var(y)


def test_get_set_params_clone():
    m = lgb.LGBMRegressor(n_estimators=10, num_leaves=7)
    params = m.get_params()
    assert params["num_leaves"] == 7
    m.set_params(num_leaves=15)
    assert m.get_params()["num_leaves"] == 15
    from sklearn.base import clone
    try:
        m2 = clone(m)
        assert m2.get_params()["num_leaves"] == 15
    except Exception:
        pass  # sklearn clone requires full estimator protocol; params API suffices


def test_class_weight_balanced():
    X, y = make_synthetic_binary(n=3000)
    # unbalance the training data
    keep = (y == 0) | (np.random.RandomState(0).rand(len(y)) < 0.3)
    m = lgb.LGBMClassifier(n_estimators=20, class_weight="balanced", verbosity=-1)
    m.fit(X[keep], y[keep])
    assert m.score(X, y) > 0.7
