"""Sparse (CSR/CSC) ingestion without densify.

Reference: src/io/sparse_bin.hpp, bin.h:482 (MultiValBin) — the TPU design
keeps the EFB-bundled uint8[N, G] layout and builds it straight from CSC in
O(nnz); these tests pin exact equality with the densified path.
"""
import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")

import lightgbm_tpu as lgb


def _make_sparse(n=2500, f=30, seed=0, with_nan=False):
    rng = np.random.RandomState(seed)
    X = np.zeros((n, f))
    X[:, 0] = rng.randn(n)                       # one dense column
    for j in range(1, f):
        nz = rng.choice(n, size=max(3, n // 40), replace=False)
        X[nz, j] = rng.randn(len(nz)) * (j % 3 + 1)
    if with_nan:
        X[::17, 0] = np.nan
    y = (np.nan_to_num(X[:, 0]) + X[:, 1] - X[:, 2] > 0).astype(float)
    return X, scipy_sparse.csr_matrix(X), y


@pytest.mark.parametrize("with_nan", [False, True])
def test_sparse_binning_matches_dense(with_nan):
    X, Xs, y = _make_sparse(with_nan=with_nan)
    dd = lgb.Dataset(X.copy(), label=y).construct()
    ds = lgb.Dataset(Xs, label=y).construct()
    bd, bs = dd.binned, ds.binned
    assert bd.group_features == bs.group_features
    assert np.array_equal(bd.bins, bs.bins)
    assert np.array_equal(bd.group_offsets, bs.group_offsets)
    assert np.array_equal(bd.feature_offsets, bs.feature_offsets)
    for md, ms in zip(bd.bin_mappers, bs.bin_mappers):
        assert np.array_equal(md.upper_bounds, ms.upper_bounds)
        assert md.num_bins == ms.num_bins
        assert md.default_bin == ms.default_bin
        assert md.missing_type == ms.missing_type
    # EFB actually bundled something (the point of the sparse layout)
    assert len(bd.group_features) < X.shape[1]


def test_sparse_training_and_predict_match_dense():
    X, Xs, y = _make_sparse()
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    b1 = lgb.train(p, lgb.Dataset(X.copy(), label=y), num_boost_round=8)
    b2 = lgb.train(p, lgb.Dataset(Xs, label=y), num_boost_round=8)
    assert b1.model_to_string() == b2.model_to_string()
    np.testing.assert_array_equal(b1.predict(X[:400], raw_score=True),
                                  b2.predict(Xs[:400], raw_score=True))


@pytest.mark.slow
def test_sparse_valid_set_and_subset():
    X, Xs, y = _make_sparse()
    tr = lgb.Dataset(Xs[:2000], label=y[:2000])
    va = lgb.Dataset(Xs[2000:], label=y[2000:], reference=tr)
    ev = {}
    lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1},
              tr, num_boost_round=6, valid_sets=[va], valid_names=["v"],
              callbacks=[lgb.record_evaluation(ev)])
    assert len(ev["v"]["binary_logloss"]) == 6
    sub = tr.subset(np.arange(0, 1000))
    sub.construct()
    assert sub.num_data() == 1000


def test_sparse_zero_as_missing():
    X, Xs, y = _make_sparse()
    p = {"zero_as_missing": True}
    dd = lgb.Dataset(X.copy(), label=y, params=p).construct()
    ds = lgb.Dataset(Xs, label=y, params=p).construct()
    assert np.array_equal(dd.binned.bins, ds.binned.bins)
