"""Fused streaming route+hist kernel correctness (CPU interpret mode).

The kernel's ROUTING is integer arithmetic and must match the XLA oracle
EXACTLY; its histogram uses a two-pass bf16 weight split (hi+lo) and is
checked to ~1e-3 relative (reference analog: the CUDA learner's float hists
vs the CPU double hists, src/treelearner/cuda/*)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.grow import feature_local_bin
from lightgbm_tpu.ops.histogram import _hist_segsum
from lightgbm_tpu.pallas import stream_kernel
from lightgbm_tpu.pallas.stream_kernel import (build_route_tables, pack_bins_T,
                                               route_and_hist)


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = stream_kernel._INTERPRET
    stream_kernel._INTERPRET = True
    yield
    stream_kernel._INTERPRET = old


def _dataset(n=2000, seed=11):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    X[:, 4] = rs.randint(0, 7, n)
    X[:, 5] = rs.randint(0, 3, n)
    X[rs.rand(n) < 0.1, 0] = np.nan
    y = ((X[:, 1] > 0) ^ (np.nan_to_num(X[:, 0]) > 0.3)
         | (X[:, 4] == 2)).astype(np.float64)
    ds = lgb.Dataset(X, label=y, categorical_feature=[4, 5],
                     params={"max_bin": 31, "verbosity": -1})
    ds.construct()
    return ds, X, y


def _xla_route(bins, leaf_id, routing, leaf_chosen, leaf_feat, leaf_thr,
               leaf_dir, leaf_new, leaf_bits, Bmax):
    r_chosen = leaf_chosen[leaf_id]
    r_feat = leaf_feat[leaf_id]
    r_grp = routing.feat_group[r_feat]
    gb = jnp.take_along_axis(bins, r_grp[:, None].astype(jnp.int32), axis=1)[:, 0]
    fb = feature_local_bin(gb, r_feat, routing)
    r_thr = leaf_thr[leaf_id]
    r_dir = leaf_dir[leaf_id]
    is_cat = (r_dir & 2) != 0
    default_left = (r_dir & 1) != 0
    is_nan = (routing.nan_bin[r_feat] >= 0) & (fb == routing.nan_bin[r_feat])
    go_left_num = jnp.where(is_nan, default_left, fb <= r_thr)
    go_left_cat = leaf_bits.reshape(-1)[leaf_id * Bmax + fb]
    go_left = jnp.where(is_cat, go_left_cat, go_left_num)
    return jnp.where(r_chosen & ~go_left, leaf_new[leaf_id], leaf_id), go_left


@pytest.mark.slow
def test_route_exact_and_hist_close():
    ds, X, y = _dataset()
    dd = ds.device_data()
    bins = dd.bins
    routing = dd.routing
    N, G = bins.shape
    Bmax = dd.max_bins
    L, S = 8, 4
    rs = np.random.RandomState(3)
    i32 = jnp.int32

    leaf_id = jnp.asarray(rs.randint(0, 4, N).astype(np.int32))
    # leaf 0: numeric split on feature 1; leaf 1: categorical on feature 4;
    # leaf 2: numeric split on (possibly bundled/NaN) feature 0; leaf 3: no split
    leaf_chosen = jnp.asarray(np.array([1, 1, 1, 0, 0, 0, 0, 0], bool))
    leaf_feat = jnp.asarray(np.array([1, 4, 0, 0, 0, 0, 0, 0], np.int32))
    leaf_thr = jnp.asarray(np.array([7, 2, 3, 0, 0, 0, 0, 0], np.int32))
    leaf_dir = jnp.asarray(np.array([0, 2, 1, 0, 0, 0, 0, 0], np.int32))
    leaf_new = jnp.asarray(np.array([4, 5, 6, 0, 0, 0, 0, 0], np.int32))
    bits_np = np.zeros((L, Bmax), bool)
    bits_np[1, [1, 2, 4]] = True          # cat leaf: bins 1,2,4 go left
    leaf_bits = jnp.asarray(bits_np)

    grad = jnp.asarray(rs.randn(N).astype(np.float32))
    hess = jnp.abs(grad) + 0.25
    cnt = jnp.asarray((rs.rand(N) > 0.3).astype(np.float32))
    grad = grad * cnt
    hess = hess * cnt

    # oracle: XLA route then segsum hist of the smaller-child slots
    new_leaf_ref, _ = _xla_route(bins, leaf_id, routing, leaf_chosen, leaf_feat,
                                 leaf_thr, leaf_dir, leaf_new, leaf_bits, Bmax)
    # slots: smaller child of split i gets slot i; say children 4,5,6 are smaller
    slot_map = np.full(L, -1, np.int32)
    for i, smaller in enumerate([4, 5, 6]):
        slot_map[smaller] = i
    slot_ref = jnp.asarray(slot_map)[new_leaf_ref]
    hist_ref = _hist_segsum(bins, slot_ref, grad, hess, cnt, S, Bmax)

    # streaming kernel
    slay = pack_bins_T(bins)
    n_pad = slay.n_pad
    w_T = jnp.zeros((8, n_pad), jnp.float32)
    w_T = w_T.at[0, :N].set(grad).at[1, :N].set(hess).at[2, :N].set(cnt)
    # smaller child is the NEW (right) child for all three splits
    sl1 = jnp.zeros(L, i32)
    sr1 = jnp.zeros(L, i32).at[0].set(1).at[1].set(2).at[2].set(3)
    tabs = build_route_tables(leaf_chosen.astype(i32), leaf_feat, leaf_thr,
                              leaf_dir, leaf_new, sl1, sr1, jnp.zeros(L, i32),
                              routing, L)
    Bpad = -(-Bmax // 8) * 8
    bits_T = jnp.pad(leaf_bits.astype(jnp.bfloat16),
                     ((0, 0), (0, Bpad - Bmax))).T
    leaf_row = jnp.pad(leaf_id, (0, n_pad - N)).reshape(1, -1)
    new_leaf, hist, slot_cnt = route_and_hist(slay.bins_T, leaf_row, w_T, tabs,
                                              bits_T, S, Bmax, G, L,
                                              has_cat=True)

    np.testing.assert_array_equal(np.asarray(new_leaf[0, :N]),
                                  np.asarray(new_leaf_ref))
    np.testing.assert_allclose(np.asarray(hist),
                               np.asarray(hist_ref[..., :2]),
                               rtol=2e-3, atol=2e-3)
    # per-slot exact counts (0/1 weights are bf16-exact); any single group's
    # bins partition each slot's rows
    np.testing.assert_allclose(np.asarray(slot_cnt),
                               np.asarray(hist_ref[:, 0, :, 2].sum(-1)),
                               atol=1e-6)


def test_root_pass_matches_segsum():
    ds, X, y = _dataset(n=1500, seed=5)
    dd = ds.device_data()
    bins = dd.bins
    N, G = bins.shape
    Bmax = dd.max_bins
    L = 8
    rs = np.random.RandomState(0)
    grad = jnp.asarray(rs.randn(N).astype(np.float32))
    hess = jnp.abs(grad) + 0.5
    cnt = jnp.ones(N, jnp.float32)

    slay = pack_bins_T(bins)
    n_pad = slay.n_pad
    w_T = jnp.zeros((8, n_pad), jnp.float32)
    w_T = w_T.at[0, :N].set(grad).at[1, :N].set(hess).at[2, :N].set(cnt)
    zL = jnp.zeros(L, jnp.int32)
    tabs = build_route_tables(zL, zL, zL, zL, zL, zL, zL, zL.at[0].set(1),
                              dd.routing, L)
    Bpad = -(-Bmax // 8) * 8
    bits = jnp.zeros((Bpad, L), jnp.bfloat16)
    leaf_row = jnp.zeros((1, n_pad), jnp.int32)
    new_leaf, hist, slot_cnt = route_and_hist(slay.bins_T, leaf_row, w_T, tabs,
                                              bits, 1, Bmax, G, L, has_cat=True)
    hist_ref = _hist_segsum(bins, jnp.zeros(N, jnp.int32), grad, hess, cnt,
                            1, Bmax)
    np.testing.assert_array_equal(np.asarray(new_leaf[0, :N]), 0)
    np.testing.assert_allclose(np.asarray(hist),
                               np.asarray(hist_ref[..., :2]),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(slot_cnt), [float(N)], atol=1e-6)


def test_int8_hist_exact():
    """int_weights path: integer grad/hess rows accumulate EXACTLY (int32)."""
    ds, X, y = _dataset(n=1500, seed=5)
    dd = ds.device_data()
    bins = dd.bins
    N, G = bins.shape
    Bmax = dd.max_bins
    L = 8
    rs = np.random.RandomState(0)
    gi = rs.randint(-32, 33, N).astype(np.float32)   # integer-valued
    hi = rs.randint(0, 33, N).astype(np.float32)
    cnt = jnp.ones(N, jnp.float32)

    slay = pack_bins_T(bins)
    n_pad = slay.n_pad
    w_T = jnp.zeros((8, n_pad), jnp.float32)
    w_T = (w_T.at[0, :N].set(jnp.asarray(gi)).at[1, :N].set(jnp.asarray(hi))
              .at[2, :N].set(cnt))
    zL = jnp.zeros(L, jnp.int32)
    tabs = build_route_tables(zL, zL, zL, zL, zL, zL, zL, zL.at[0].set(1),
                              dd.routing, L)
    Bpad = -(-Bmax // 8) * 8
    bits = jnp.zeros((Bpad, L), jnp.bfloat16)
    leaf_row = jnp.zeros((1, n_pad), jnp.int32)
    _, hist, slot_cnt = route_and_hist(slay.bins_T, leaf_row, w_T, tabs,
                                       bits, 1, Bmax, G, L, has_cat=True,
                                       int_weights=True)
    hist_ref = _hist_segsum(bins, jnp.zeros(N, jnp.int32), jnp.asarray(gi),
                            jnp.asarray(hi), cnt, 1, Bmax)
    assert hist.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(hist, np.float64),
                                  np.asarray(hist_ref[..., :2], np.float64))
    np.testing.assert_allclose(np.asarray(slot_cnt), [float(N)], atol=1e-6)


@pytest.mark.slow
def test_stream_end_to_end_close():
    """Full training with the stream backend matches segsum predictions to
    bf16-accumulation tolerance."""
    ds_params = {"max_bin": 31, "verbosity": -1}
    rs = np.random.RandomState(11)
    n = 1200
    X = rs.randn(n, 6)
    X[:, 4] = rs.randint(0, 7, n)
    X[rs.rand(n) < 0.1, 0] = np.nan
    y = ((X[:, 1] > 0) ^ (np.nan_to_num(X[:, 0]) > 0.3)
         | (X[:, 4] == 2)).astype(np.float64)
    preds = {}
    for backend in ("segsum", "stream"):
        ds = lgb.Dataset(X, label=y, categorical_feature=[4],
                         params=ds_params)
        bst = lgb.train({"objective": "binary", "num_leaves": 8,
                         "verbosity": -1, "max_bin": 31,
                         "min_data_in_leaf": 5, "hist_backend": backend,
                         "max_splits_per_round": 4}, ds, num_boost_round=3)
        preds[backend] = bst.predict(X, raw_score=True)
    # bf16 two-pass hist sums can flip near-tie splits for a few rows; demand
    # distribution-level agreement rather than per-row equality
    diff = np.abs(preds["stream"] - preds["segsum"])
    assert np.mean(diff < 0.05) > 0.95
    assert np.corrcoef(preds["stream"], preds["segsum"])[0, 1] > 0.99


@pytest.mark.slow
def test_stream_final_sprint_completes_tree():
    """num_leaves >= 130 with the stream backend engages the FINAL-SPRINT
    schedule (ops/grow.py: the hist loop exits once one route-only round can
    finish, batching up to 2S splits without histograms).  The tree must
    still reach the full leaf budget with exact leaf counts."""
    rs = np.random.RandomState(5)
    n = 6000
    X = rs.randn(n, 8)
    y = (X[:, 0] + np.sin(3 * X[:, 1]) + 0.3 * rs.randn(n) > 0).astype(
        np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbosity": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 140,
                     "verbosity": -1, "max_bin": 63, "min_data_in_leaf": 2,
                     "hist_backend": "stream", "max_splits_per_round": 64},
                    ds, num_boost_round=2)
    dumped = bst.dump_model()
    for t in dumped["tree_info"]:
        assert t["num_leaves"] == 140
        # exact per-leaf counts from the sprint round's count dot
        counts = []
        def walk(node):
            if "leaf_count" in node:
                counts.append(node["leaf_count"])
            else:
                walk(node["left_child"]); walk(node["right_child"])
        walk(t["tree_structure"])
        assert sum(counts) == n
        assert min(counts) >= 2
    # quality smoke: the model actually separates the classes
    auc_ranks = np.argsort(np.argsort(bst.predict(X, raw_score=True)))
    pos = auc_ranks[y > 0.5].mean()
    neg = auc_ranks[y < 0.5].mean()
    assert pos > neg + n / 10


def test_bucketed_m_axis_exact():
    """The bucketed one-hot M-axis (bin_buckets runs over bucket-sorted
    groups) must produce BIT-IDENTICAL int32 histograms, routes and counts
    to the uniform G*Bmax layout on mixed-cardinality data."""
    rs = np.random.RandomState(7)
    n = 1800
    X = np.column_stack([
        rs.randint(0, 2, (n, 2)).astype(float),      # 8-bucket
        rs.randint(0, 10, (n, 3)).astype(float),     # 16-bucket
        rs.randint(0, 25, (n, 2)).astype(float),     # 32-bucket
        rs.randn(n, 3)])                             # 64-bucket
    y = (X[:, 0] + X[:, 9] > 0.5).astype(float)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbosity": -1})
    ds.construct()
    dd = ds.device_data()
    bins = dd.bins
    N, G = bins.shape
    Bmax = dd.max_bins
    L = 8
    counts = np.asarray(ds.binned.group_bin_counts)
    # groups must be bucket-sorted descending by construction
    buckets = []
    for cnt in counts:
        b = 8
        while b < int(cnt):
            b *= 2
        if buckets and buckets[-1][0] == b:
            buckets[-1][1] += 1
        else:
            buckets.append([b, 1])
    bb = tuple((int(b), int(g)) for b, g in buckets)
    assert len(bb) >= 3 and sum(g for _, g in bb) == G
    assert [b for b, _ in bb] == sorted([b for b, _ in bb], reverse=True)

    gi = rs.randint(-32, 33, N).astype(np.float32)
    hi = rs.randint(0, 33, N).astype(np.float32)
    slay = pack_bins_T(bins)
    n_pad = slay.n_pad
    w_T = jnp.zeros((8, n_pad), jnp.float32)
    w_T = (w_T.at[0, :N].set(jnp.asarray(gi)).at[1, :N].set(jnp.asarray(hi))
              .at[2, :N].set(1.0))
    zL = jnp.zeros(L, jnp.int32)
    # a real split on feature 0 so routing is exercised too
    chosen = zL.at[0].set(1)
    feats = zL
    thrs = zL.at[0].set(0)
    newid = zL.at[0].set(1)
    tabs = build_route_tables(chosen, feats, thrs, zL, newid,
                              zL.at[0].set(1), zL, zL, dd.routing, L)
    Bpad = -(-Bmax // 8) * 8
    bits = jnp.zeros((Bpad, L), jnp.bfloat16)
    leaf_row = jnp.zeros((1, n_pad), jnp.int32)
    args = (slay.bins_T, leaf_row, w_T, tabs, bits, 2, Bmax, G, L)
    kw = dict(has_cat=False, int_weights=True)
    nl_u, hist_u, cnt_u = route_and_hist(*args, **kw)
    nl_b, hist_b, cnt_b = route_and_hist(*args, bin_buckets=bb, **kw)
    np.testing.assert_array_equal(np.asarray(nl_u), np.asarray(nl_b))
    np.testing.assert_array_equal(np.asarray(hist_u), np.asarray(hist_b))
    np.testing.assert_allclose(np.asarray(cnt_u), np.asarray(cnt_b),
                               atol=1e-6)
