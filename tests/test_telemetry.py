"""Telemetry subsystem: span nesting, Chrome-trace roundtrip, per-iteration
training records, recompile watchdog, straggler aggregation, and the
zero-overhead disabled path — plus the timer/log satellite fixes."""
import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.telemetry as tel
from lightgbm_tpu.telemetry.tracer import _NULL_SPAN
from lightgbm_tpu.utils import log as logmod

from conftest import make_synthetic_regression


class _Recorder:
    def __init__(self):
        self.infos = []
        self.warnings = []

    def info(self, msg):
        self.infos.append(str(msg))

    def warning(self, msg):
        self.warnings.append(str(msg))


@pytest.fixture
def telemetry():
    tel.reset()
    tel.reset_watchdog()
    tel.configure(enabled=True)
    yield tel
    tel.disable()
    tel.reset()
    tel.reset_watchdog()
    tel.configure(enabled=False, metrics_out="", trace_out="")


@pytest.fixture
def logrec():
    rec = _Recorder()
    old = (logmod._logger, logmod._info_method_name,
           logmod._warning_method_name)
    old_verbosity = logmod.get_verbosity()
    logmod.register_logger(rec)
    logmod.set_verbosity(1)   # verbosity is process-global; pin it here
    yield rec
    logmod._logger, logmod._info_method_name, \
        logmod._warning_method_name = old
    logmod.set_verbosity(old_verbosity)


def _train_params(**overrides):
    p = {"objective": "regression", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbosity": -1, "telemetry": True}
    p.update(overrides)
    return p


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

def test_span_nesting_and_events(telemetry):
    with tel.span("outer", kind="test"):
        with tel.span("inner"):
            time.sleep(0.001)
        with tel.span("inner"):
            pass
    events = tel.global_tracer.events
    names = [(e["name"], e["ph"]) for e in events]
    assert names == [("outer", "B"), ("inner", "B"), ("inner", "E"),
                     ("inner", "B"), ("inner", "E"), ("outer", "E")]
    # begin/end timestamps nest: outer B <= inner B, inner E <= outer E
    outer_b, outer_e = events[0]["ts"], events[-1]["ts"]
    assert outer_b <= events[1]["ts"] <= events[2]["ts"] <= outer_e
    # attributes ride on the begin event
    assert events[0]["args"] == {"kind": "test"}
    phases = tel.global_tracer.phase_snapshot()
    assert phases["inner"] <= phases["outer"]
    assert tel.global_tracer.phase_counts()["inner"] == 2


def test_trace_export_roundtrip(telemetry, tmp_path):
    with tel.span("region"):
        tel.instant("marker", detail=1)
        tel.counter_sample("track", value=3.5)
    path = str(tmp_path / "trace.json")
    tel.export_trace(path)
    blob = json.loads(open(path).read())
    # Chrome trace-event envelope: Perfetto loads {"traceEvents": [...]}
    assert isinstance(blob["traceEvents"], list)
    assert blob["displayTimeUnit"] == "ms"
    phs = set()
    for ev in blob["traceEvents"]:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("B", "E", "X", "i", "C", "M")
        assert isinstance(ev["pid"], int)
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        phs.add(ev["ph"])
    assert {"B", "E", "i", "C", "M"} <= phs
    # B/E balanced per thread
    for tid in {e["tid"] for e in blob["traceEvents"] if e["ph"] in "BE"}:
        seq = [e["ph"] for e in blob["traceEvents"]
               if e.get("tid") == tid and e["ph"] in "BE"]
        depth = 0
        for ph in seq:
            depth += 1 if ph == "B" else -1
            assert depth >= 0
        assert depth == 0


def test_zero_overhead_when_disabled():
    tel.disable()
    tel.reset()
    # the disabled fast path hands back ONE shared no-op object: a single
    # boolean check, no allocation, nothing recorded
    assert tel.span("a") is tel.span("b") is _NULL_SPAN
    with tel.span("a"):
        pass
    tel.instant("x")
    tel.counter_sample("x", v=1)
    tel.inc("c")
    tel.gauge("g", 1.0)
    tel.observe("h", 0.1)
    tel.record({"event": "x"})
    assert tel.global_tracer.events == []
    snap = tel.global_registry.snapshot()
    assert snap["counters"] == {} and snap["gauges"] == {}
    assert snap["num_records"] == 0


def test_param_scoped_telemetry_does_not_leak_across_boosters(tmp_path):
    """Model B trained without telemetry params must not inherit model A's
    sink or instrumentation (param-driven enablement is per-model)."""
    sink = str(tmp_path / "a.jsonl")
    X, y = make_synthetic_regression(n=300, f=4)
    try:
        lgb.train(_train_params(telemetry_out=sink), lgb.Dataset(X, label=y),
                  num_boost_round=2)
        assert len(open(sink).readlines()) == 2
        lgb.train({"objective": "regression", "num_leaves": 7,
                   "min_data_in_leaf": 5, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2)
        assert not tel.enabled()
        assert len(open(sink).readlines()) == 2   # no contamination
    finally:
        tel.configure(enabled=False, metrics_out="", trace_out="")
        tel.reset()


def test_train_disabled_emits_nothing():
    tel.disable()
    tel.reset()
    X, y = make_synthetic_regression(n=300, f=4)
    lgb.train(_train_params(telemetry=False), lgb.Dataset(X, label=y),
              num_boost_round=2)
    assert tel.global_registry.records == []
    assert tel.global_tracer.events == []


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_metrics_registry_instruments(telemetry):
    tel.inc("c", 2)
    tel.inc("c")
    tel.gauge("g", 4.25)
    tel.observe("h", 0.002)
    tel.observe("h", 0.2)
    snap = tel.global_registry.snapshot()
    assert snap["counters"]["c"] == 3
    assert snap["gauges"]["g"] == 4.25
    h = snap["histograms"]["h"]
    assert h["count"] == 2
    assert h["min_s"] == pytest.approx(0.002)
    assert h["max_s"] == pytest.approx(0.2)
    assert h["mean_s"] == pytest.approx(0.101)


def test_quantiles_value_on_bucket_bound(telemetry):
    """A quantile landing EXACTLY on a cumulative-bucket boundary must
    report from the bucket holding the value, not the next one.  With 19
    of 20 samples at the 2.0 bound, ``0.95 * 20`` is 19.000000000000004
    in binary — an unguarded walk steps past bucket 2.0 and interpolates
    inside (2.0, 4.0]."""
    for _ in range(19):
        tel.observe("qb", 2.0, bounds=(1.0, 2.0, 4.0))
    tel.observe("qb", 5.0, bounds=(1.0, 2.0, 4.0))
    q = tel.quantiles("qb", qs=(0.5, 0.95, 0.99))
    assert q["p50"] == 2.0          # clamped up to the observed min
    assert q["p95"] == 2.0          # ON the bound, not past it
    assert q["p99"] == pytest.approx(4.8)   # inside the last bucket


def test_quantiles_single_bucket_degenerate(telemetry):
    tel.observe("q1", 0.5, bounds=(1.0,))
    q = tel.quantiles("q1", qs=(0.5, 0.99))
    # every quantile clamps into [min, max] of the observations
    assert q["p50"] == 0.5 and q["p99"] == 0.5


# ---------------------------------------------------------------------------
# per-iteration training records
# ---------------------------------------------------------------------------

def test_train_emits_iteration_records(telemetry, tmp_path):
    metrics_path = str(tmp_path / "metrics.jsonl")
    trace_path = str(tmp_path / "trace.json")
    rounds = 4
    X, y = make_synthetic_regression(n=500, f=5)
    bst = lgb.train(
        _train_params(telemetry_out=metrics_path, trace_out=trace_path),
        lgb.Dataset(X, label=y), num_boost_round=rounds)
    # one JSONL record per boosting iteration
    lines = [json.loads(l) for l in open(metrics_path)]
    iters = [r for r in lines if r.get("event") == "iteration"]
    assert len(iters) == rounds
    for i, r in enumerate(iters):
        assert r["iteration"] == i + 1
        assert r["wall_s"] > 0
        assert 2 <= r["num_leaves"] <= 7
        assert r["phases"]  # boosting/grow splits present
        assert "peak_hbm_gb" in r or "device_hbm_gb" in r
        assert "host_rss_gb" in r
    assert any("boosting_s" in r["phases"] for r in iters)
    assert any("grow_s" in r["phases"] for r in iters)
    # trace written by train() and Perfetto-loadable, with per-iter spans
    blob = json.loads(open(trace_path).read())
    iter_begins = [e for e in blob["traceEvents"]
                   if e["name"] == "GBDT::Iteration" and e["ph"] == "B"]
    assert len(iter_begins) == rounds
    # summary rolls everything up
    s = bst.telemetry_summary()
    assert s["train"]["iterations_recorded"] == rounds
    assert s["train"]["total_s"] > 0
    assert s["recompiles"]["grow_tree"]["compiles"] >= 1
    assert "GBDT::Iteration" in s["phases"]
    assert s["counters"]["train/iterations"] == rounds


def test_log_telemetry_callback(telemetry, logrec):
    X, y = make_synthetic_regression(n=300, f=4)
    lgb.train(_train_params(verbosity=1), lgb.Dataset(X, label=y),
              num_boost_round=3, callbacks=[lgb.log_telemetry(period=1)])
    lines = [m for m in logrec.infos if "[telemetry]" in m]
    assert len(lines) == 3
    assert "iter" in lines[0] and "ms" in lines[0]


# ---------------------------------------------------------------------------
# recompile watchdog
# ---------------------------------------------------------------------------

def test_watchdog_counts_and_warns_on_shape_change(telemetry, logrec):
    f = tel.watched_jit(lambda x: x * 2.0, name="unit_fn", warn_after=1)
    f(jnp.ones(4))
    f(jnp.zeros(4))          # cache hit: same shape/dtype, no retrace
    assert tel.recompile_counts()["unit_fn"] == 1
    assert logrec.warnings == []
    f(jnp.ones(8))           # forced shape change -> retrace -> warning
    assert tel.recompile_counts()["unit_fn"] == 2
    warns = [w for w in logrec.warnings if "unit_fn" in w]
    assert len(warns) == 1
    assert "recompiled" in warns[0]
    assert "float32[8]" in warns[0]      # offending shapes/dtypes included
    # the warning also lands in the trace as an instant event
    names = [e["name"] for e in tel.global_tracer.events if e["ph"] == "i"]
    assert "recompile:unit_fn" in names


def test_watchdog_fires_on_midtraining_retrace(telemetry, logrec):
    """reset_parameter mid-training re-jits the grower — the watchdog must
    flag the retrace of the same (engine, entry-point) pair."""
    X, y = make_synthetic_regression(n=400, f=5)
    cb = lgb.reset_parameter(lambda_l2=[0.0, 0.0, 0.5, 0.5])
    lgb.train(_train_params(telemetry_recompile_threshold=1, verbosity=0),
              lgb.Dataset(X, label=y), num_boost_round=4, callbacks=[cb])
    warns = [w for w in logrec.warnings
             if "grow_tree" in w and "recompiled" in w]
    assert warns, f"no recompile warning in {logrec.warnings!r}"
    s = tel.watchdog_summary()
    assert s["grow_tree"]["max_per_entry"] >= 2
    assert s["grow_tree"]["warned"] >= 1


def test_watchdog_silent_for_fresh_models(telemetry, logrec):
    """Two independent boosters each compile once: per-entry counters must
    not bleed across engines (a fresh model is not a retrace)."""
    X, y = make_synthetic_regression(n=300, f=4)
    for n in (300, 200):
        lgb.train(_train_params(telemetry_recompile_threshold=1,
                                verbosity=0),
                  lgb.Dataset(X[:n], label=y[:n]), num_boost_round=2)
    assert [w for w in logrec.warnings if "grow_tree" in w] == []
    assert tel.watchdog_summary()["grow_tree"]["max_per_entry"] == 1


# ---------------------------------------------------------------------------
# multi-host straggler aggregation
# ---------------------------------------------------------------------------

def test_straggler_report_single_host(telemetry):
    from lightgbm_tpu.parallel.straggler import straggler_report
    rep = straggler_report([0.1, 0.11, 0.09])
    assert rep["hosts"] == 1
    assert rep["median_host_mean_s"] == pytest.approx(0.1, rel=0.1)
    assert rep["skew"] == pytest.approx(1.0)
    assert rep in tel.global_registry.records


def test_straggler_report_flags_slow_host(telemetry, logrec):
    from lightgbm_tpu.parallel.straggler import straggler_report
    stats = np.array([[10, 0.10, 0.12],
                      [10, 0.10, 0.11],
                      [10, 0.30, 0.40],
                      [10, 0.11, 0.12]])
    rep = straggler_report([0.1] * 10, warn_skew=1.25,
                           _all_host_stats=stats)
    assert rep["hosts"] == 4
    assert rep["slowest_host"] == 2
    assert rep["skew"] >= 2.0
    assert any("straggler" in w for w in logrec.warnings)
    # balanced hosts: info line, no warning
    logrec.warnings.clear()
    even = np.array([[10, 0.10, 0.12], [10, 0.105, 0.11]])
    rep = straggler_report([0.1] * 10, warn_skew=1.25, _all_host_stats=even)
    assert rep["skew"] < 1.25
    assert not logrec.warnings


@pytest.mark.slow
def test_straggler_reports_in_multiprocess_training(tmp_path):
    """Real 2-process jax.distributed run: the straggler allgather fires
    every K iterations and rank 0's summary carries the report."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = make_synthetic_regression(n=1200, f=6)
    data_path = str(tmp_path / "train.csv")
    np.savetxt(data_path, np.column_stack([y, X]), delimiter=",")
    from lightgbm_tpu.parallel.cluster import train_distributed
    from lightgbm_tpu.utils.log import LightGBMError
    try:
        bst = train_distributed(
            {"objective": "regression", "num_leaves": 7,
             "min_data_in_leaf": 5, "verbosity": -1, "telemetry": True,
             "telemetry_straggler_every": 2},
            data_path, num_boost_round=6, num_processes=2)
    except LightGBMError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip("jax CPU backend lacks multiprocess collectives")
        raise
    s = bst.telemetry_summary_
    assert s["train"]["iterations_recorded"] == 6
    assert "straggler" in s, f"no straggler report in {list(s)}"
    assert s["straggler"]["hosts"] == 2
    assert s["straggler"]["skew"] >= 1.0


# ---------------------------------------------------------------------------
# satellite: Timer fixes
# ---------------------------------------------------------------------------

def test_timer_env_read_lazily(monkeypatch):
    from lightgbm_tpu.utils.timer import Timer
    t = Timer()
    monkeypatch.delenv("LIGHTGBM_TPU_TIMETAG", raising=False)
    assert not t.enabled
    # env set AFTER construction must be honored (was frozen at import)
    monkeypatch.setenv("LIGHTGBM_TPU_TIMETAG", "1")
    assert t.enabled
    monkeypatch.setenv("LIGHTGBM_TPU_TIMETAG", "0")
    assert not t.enabled
    t.enable()
    assert t.enabled            # override beats env
    t.disable()
    monkeypatch.setenv("LIGHTGBM_TPU_TIMETAG", "1")
    assert not t.enabled
    t.reset_enabled()
    assert t.enabled


def test_timer_report_sorted_by_total_with_mean():
    from lightgbm_tpu.utils.timer import Timer
    t = Timer()
    t.enable()
    with t.scope("cold"):
        pass
    with t.scope("hot"):
        time.sleep(0.02)
    with t.scope("warm"):
        time.sleep(0.005)
    lines = t.report().splitlines()
    assert [l.split(":")[0] for l in lines] == ["hot", "warm", "cold"]
    assert all("ms/call" in l for l in lines)


# ---------------------------------------------------------------------------
# satellite: log handler guard
# ---------------------------------------------------------------------------

def test_no_duplicate_handlers_on_reimport():
    import importlib
    import logging
    shared = logging.getLogger("lightgbm_tpu")
    before = list(shared.handlers)
    importlib.reload(logmod)     # simulates a second import of the module
    assert shared.handlers == before
    # a pre-configured level must survive re-import untouched
    old_level = shared.level
    try:
        shared.setLevel(logging.ERROR)
        importlib.reload(logmod)
        assert shared.level == logging.ERROR
    finally:
        shared.setLevel(old_level)
