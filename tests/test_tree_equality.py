"""Sharded training must produce the SAME TREES as serial training.

Reference: the distributed learners reduce exact histograms, so they pick the
same splits as the serial learner (data_parallel_tree_learner.cpp:285-299,
feature_parallel_tree_learner.cpp:25-83). Here GSPMD partitioning inserts the
collectives; the trees must still match the serial run (model-string compare,
not accuracy fuzz)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow  # heavy multi-model tier (PERF.md test tiers)


def _data(n=4000, f=10, seed=13):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = (X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * rs.randn(n))
    return X, y


def _train_str(X, y, tree_learner, seed_extra=0, **extra):
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5, "tree_learner": tree_learner,
              "max_bin": 63, **extra}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    return bst.model_to_string()


@pytest.mark.parametrize("learner", ["data", "feature"])
def test_sharded_trees_equal_serial(learner):
    X, y = _data()
    s_serial = _train_str(X, y, "serial")
    s_shard = _train_str(X, y, learner)

    def strip_noise(s):
        # timestamps/float formatting identical; compare verbatim
        return s

    if strip_noise(s_shard) != strip_noise(s_serial):
        # diagnose: compare per-tree split structure before failing
        import re
        feats_a = re.findall(r"split_feature=([^\n]*)", s_serial)
        feats_b = re.findall(r"split_feature=([^\n]*)", s_shard)
        assert feats_a == feats_b, (
            f"{learner}-parallel chose different split features than serial")
        thr_a = re.findall(r"\nthreshold=([^\n]*)", s_serial)
        thr_b = re.findall(r"\nthreshold=([^\n]*)", s_shard)
        assert thr_a == thr_b, (
            f"{learner}-parallel chose different thresholds than serial")
        # remaining diff would be leaf-value float noise from reduction order
        va = re.findall(r"leaf_value=([^\n]*)", s_serial)
        vb = re.findall(r"leaf_value=([^\n]*)", s_shard)
        for a, b in zip(va, vb):
            # f32 reduction order differs across shards: observed relmax ~2e-5
            np.testing.assert_allclose(
                [float(x) for x in a.split()], [float(x) for x in b.split()],
                rtol=1e-4, atol=1e-5)


def test_data_parallel_with_bagging_matches_serial():
    X, y = _data(seed=7)
    s_serial = _train_str(X, y, "serial", bagging_fraction=0.8,
                          bagging_freq=1, bagging_seed=5)
    s_shard = _train_str(X, y, "data", bagging_fraction=0.8,
                         bagging_freq=1, bagging_seed=5)
    import re
    feats_a = re.findall(r"split_feature=([^\n]*)", s_serial)
    feats_b = re.findall(r"split_feature=([^\n]*)", s_shard)
    assert feats_a == feats_b
