"""Voting-parallel (PV-Tree) learner on the 8-device CPU mesh.

Reference: src/treelearner/voting_parallel_tree_learner.cpp:104 (vote
allreduce) and :396 (elected-feature histogram reduce)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=6000, f=20, seed=17):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = (X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * rs.randn(n))
    return X, y


@pytest.mark.slow
def test_voting_close_to_serial():
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5, "max_bin": 63, "top_k": 8}
    serial = lgb.train({**params, "tree_learner": "serial"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    voting = lgb.train({**params, "tree_learner": "voting"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    assert voting.engine._voting, "voting learner should be active"
    mse_s = float(np.mean((serial.predict(X) - y) ** 2))
    mse_v = float(np.mean((voting.predict(X) - y) ** 2))
    var = float(np.var(y))
    # PV-Tree is approximate: demand competitive accuracy, not identity
    assert mse_v < var * 0.2, (mse_v, var)
    assert mse_v < mse_s * 2.0 + 1e-3, (mse_v, mse_s)


@pytest.mark.slow
def test_voting_falls_back_for_categorical():
    rs = np.random.RandomState(5)
    X = rs.randn(2000, 5)
    X[:, 3] = rs.randint(0, 5, 2000)
    y = X[:, 0] + (X[:, 3] == 2)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "tree_learner": "voting",
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[3]),
                    num_boost_round=3)
    assert not bst.engine._voting
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.9
