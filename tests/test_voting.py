"""Voting-parallel (PV-Tree) learner on the 8-device CPU mesh.

Reference: src/treelearner/voting_parallel_tree_learner.cpp:104 (vote
allreduce) and :396 (elected-feature histogram reduce)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=6000, f=20, seed=17):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = (X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * rs.randn(n))
    return X, y


@pytest.mark.slow
def test_voting_close_to_serial():
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5, "max_bin": 63, "top_k": 8}
    serial = lgb.train({**params, "tree_learner": "serial"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    voting = lgb.train({**params, "tree_learner": "voting"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    assert voting.engine._voting, "voting learner should be active"
    mse_s = float(np.mean((serial.predict(X) - y) ** 2))
    mse_v = float(np.mean((voting.predict(X) - y) ** 2))
    var = float(np.var(y))
    # PV-Tree is approximate: demand competitive accuracy, not identity
    assert mse_v < var * 0.2, (mse_v, var)
    assert mse_v < mse_s * 2.0 + 1e-3, (mse_v, mse_s)


@pytest.mark.slow
def test_voting_handles_all_layouts():
    """The PV-Tree learner supports every training layout like the
    reference's (voting_parallel_tree_learner.cpp handles categorical, NaN
    and bundled features): the three test_distributed.py layouts must train
    UNDER voting (no fallback) with competitive accuracy."""
    from tests.test_distributed import _datasets

    for name, params, data_kw, ds_kw in _datasets():
        p = dict(params, num_leaves=15, verbosity=-1, min_data_in_leaf=5,
                 tree_learner="voting", top_k=6)
        ds = lgb.Dataset(data_kw["data"], label=data_kw["label"],
                         weight=data_kw.get("weight"), **ds_kw)
        bst = lgb.train(p, ds, num_boost_round=8)
        assert bst.engine._voting, f"{name}: voting learner should be active"
        serial = lgb.train(dict(p, tree_learner="serial"), lgb.Dataset(
            data_kw["data"], label=data_kw["label"],
            weight=data_kw.get("weight"), **ds_kw), num_boost_round=8)
        pred = np.asarray(bst.predict(data_kw["data"]))
        sref = np.asarray(serial.predict(data_kw["data"]))
        y = np.asarray(data_kw["label"])
        if params["objective"] == "binary":
            acc = float(np.mean((pred > 0.5) == (y > 0.5)))
            acc_s = float(np.mean((sref > 0.5) == (y > 0.5)))
            assert acc > acc_s - 0.05, (name, acc, acc_s)
        else:
            c = np.corrcoef(pred, y)[0, 1]
            c_s = np.corrcoef(sref, y)[0, 1]
            assert c > c_s - 0.05, (name, c, c_s)
