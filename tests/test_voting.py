"""Voting-parallel (PV-Tree) learner on the 8-device CPU mesh.

Reference: src/treelearner/voting_parallel_tree_learner.cpp:104 (vote
allreduce) and :396 (elected-feature histogram reduce).

Fast tier (every verify run, and the 4-device run_all_tests.sh stage):
the layout matrix — categorical, EFB bundles, NaN bins, weighted — plus
multiclass lockstep, bagging/GOSS row-compaction A/B identity, the fused
one-launch path, checkpoint/resume round-trip, and the elected-columns
comms accounting.  The slow tier keeps the larger quality-vs-serial
comparisons."""
import os

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import host_sync_count, launch_count

N_DEV = len(jax.devices())
needs_mesh = pytest.mark.skipif(N_DEV < 4, reason="needs a >=4-device mesh")


def _strip_params(model_str: str) -> str:
    return model_str.split("\nparameters:")[0]


def _structural_ok(bst, k=1):
    """Structural identity of a voting model: legal finite trees with real
    splits (PV-Tree is quality-approximate, never structure-approximate)."""
    txt = bst.model_to_string()
    trees = txt.split("Tree=")[1:]
    assert trees, "no trees in model"
    import re
    for t in trees:
        m = re.search(r"num_leaves=(\d+)", t)
        assert m and int(m.group(1)) >= 1
        for key in ("leaf_value", "split_gain", "internal_value"):
            row = re.search(rf"{key}=([^\n]*)", t)
            if row and row.group(1).strip():
                vals = np.array([float(v) for v in row.group(1).split()])
                assert np.isfinite(vals).all(), f"non-finite {key}"
    return len(trees)


def _data(n=6000, f=20, seed=17):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = (X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * rs.randn(n))
    return X, y


# ---------------------------------------------------------------------------
# fast tier: layout matrix (categorical / EFB / NaN / weights)
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize("layout", ["nan", "categorical", "efb"])
@pytest.mark.slow
def test_voting_layout_matrix(layout):
    """Every training layout trains UNDER voting (no fallback) with legal
    structure and the documented quality tolerance vs serial (PV-Tree
    trades a little split quality for O(2k*B) comms, never correctness)."""
    rs = np.random.RandomState(11)
    if layout == "nan":
        X = rs.randn(2500, 12)
        X[::7, 1] = np.nan
        y = X[:, 0] * 2 - np.nan_to_num(X[:, 1]) + 0.1 * rs.randn(2500)
        p, ds_kw = {"objective": "regression"}, {}
    elif layout == "categorical":
        X = rs.randn(2500, 10)
        X[:, 3] = rs.randint(0, 6, 2500)
        y = X[:, 0] + 2.0 * np.isin(X[:, 3], [1, 4]) + 0.1 * rs.randn(2500)
        p, ds_kw = ({"objective": "regression"},
                    {"categorical_feature": [3]})
    else:
        X = np.zeros((2200, 14))
        X[:, :4] = rs.randn(2200, 4)
        hot = rs.randint(4, 14, 2200)
        X[np.arange(2200), hot] = 1.0
        y = X[:, 0] + 2.0 * (hot == 5) - (hot == 9) + 0.05 * rs.randn(2200)
        p, ds_kw = {"objective": "regression"}, {}
    p.update({"num_leaves": 15, "verbosity": -1, "min_data_in_leaf": 5,
              "top_k": 6})
    v = lgb.train(dict(p, tree_learner="voting"),
                  lgb.Dataset(X, label=y, **ds_kw), num_boost_round=6)
    assert v.engine._voting, "voting learner should be active"
    _structural_ok(v)
    s = lgb.train(dict(p, tree_learner="serial"),
                  lgb.Dataset(X, label=y, **ds_kw), num_boost_round=6)
    mse_v = float(np.mean((np.asarray(v.predict(X)) - y) ** 2))
    mse_s = float(np.mean((np.asarray(s.predict(X)) - y) ** 2))
    # documented tolerance (docs/DISTRIBUTED.md): competitive, not equal
    assert mse_v < mse_s * 2.0 + 1e-3, (layout, mse_v, mse_s)


@needs_mesh
@pytest.mark.slow
def test_voting_multiclass_lockstep():
    """K class trees grow inside ONE jitted per-class scan under voting
    (the _grow_classes path) — legal structure, sane accuracy, and the
    stacked one-launch score update."""
    from conftest import make_synthetic_multiclass

    X, y = make_synthetic_multiclass(n=2500, f=12, k=3)
    p = {"objective": "multiclass", "num_class": 3, "num_leaves": 11,
         "verbosity": -1, "min_data_in_leaf": 5, "top_k": 6,
         "tree_learner": "voting"}
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    assert bst.engine._voting
    assert bst.num_trees() == 12
    _structural_ok(bst)
    pred = np.asarray(bst.predict(X))
    acc = float(np.mean(np.argmax(pred, axis=1) == y))
    assert acc > 0.5, acc


@needs_mesh
@pytest.mark.parametrize("sampling", ["bagging", "goss"])
@pytest.mark.slow
def test_voting_compaction_bit_identical(sampling):
    """GOSS/bagging row compaction under voting: every shard stable-
    partitions its OWN rows, the truncated tail carries exact-zero
    weights, so compacted and dense-masked models are BYTE-identical."""
    X, y = _data(n=6000, f=16)
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5, "top_k": 6, "tree_learner": "voting",
         "seed": 3}
    if sampling == "bagging":
        # fraction low enough that the 256-row capacity quantum still
        # saves >= 25% of the fullest shard at the 8-way mesh
        p.update({"bagging_fraction": 0.3, "bagging_freq": 2})
    else:
        p.update({"data_sample_strategy": "goss", "learning_rate": 0.5,
                  "top_rate": 0.1, "other_rate": 0.15})
    from tests.test_feature_parallel import _set_env
    restores = [_set_env("LGBTPU_FUSE_ITER", "0"),
                _set_env("LGBTPU_COMPACT", "off")]
    try:
        off = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=6)
        os.environ["LGBTPU_COMPACT"] = "auto"
        on = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=6)
    finally:
        for r in restores:
            r()
    assert on.engine._last_compact_rows > 0, "compaction never engaged"
    assert on.engine._last_sampled_rows > 0
    assert _strip_params(off.model_to_string()) == \
        _strip_params(on.model_to_string())


# ---------------------------------------------------------------------------
# fast tier: fused one-launch path + comms accounting
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.slow
def test_voting_fused_identity_and_dispatch():
    """Voting rides the fused one-launch iteration by default: round-1
    tree byte-equal to the unfused pipeline, <= 1 launch and 0 host
    syncs per steady-state iteration."""
    from tests.test_fused_sharded import _assert_fused_identity

    X, y = _data(n=3000, f=16)
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5, "top_k": 6, "tree_learner": "voting"}
    f = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    assert f.engine._fused_last, "voting fused path did not engage"
    from tests.test_feature_parallel import _set_env
    restore = _set_env("LGBTPU_FUSE_ITER", "0")
    try:
        u = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    finally:
        restore()
    assert not u.engine._fused_last
    _assert_fused_identity(f.model_to_string(), u.model_to_string())
    l0, s0 = launch_count(), host_sync_count()
    for _ in range(4):
        f.update()
    assert (launch_count() - l0) / 4 <= 1.5
    assert (host_sync_count() - s0) / 4 == 0.0


@needs_mesh
def test_voting_comms_elected_columns():
    """The voting payload ships <= 2k*B histogram columns per slot —
    never the O(F*B) data-parallel block (GlobalVoting :104/:396)."""
    X, y = _data(n=2000, f=24)
    top_k = 5
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "top_k": top_k, "tree_learner": "voting",
                     "telemetry": True},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    cm = bst.engine._comms_model()
    assert cm["mode"] == "voting"
    assert cm["elected_columns"] <= 2 * top_k
    eng = bst.engine
    S2 = 2 * min(eng._grow_params.max_splits_per_round,
                 eng._grow_params.num_leaves - 1)
    assert cm["hist_block_bytes"] <= \
        S2 * 2 * top_k * eng.dd.max_bins * 3 * 4
    # and strictly below the full psum block at this F
    from lightgbm_tpu.parallel.comms import hist_comms_bytes_per_round
    full = hist_comms_bytes_per_round(S2, eng.dd.num_groups,
                                      eng.dd.max_bins, cm["devices"],
                                      "psum")
    assert cm["hist_block_bytes"] < full


# ---------------------------------------------------------------------------
# fast tier: checkpoint / resume round-trip
# ---------------------------------------------------------------------------

@needs_mesh
@pytest.mark.slow
def test_voting_checkpoint_resume(tmp_path):
    """A mid-run snapshot resumes BYTE-identically under voting (the
    restored score + iteration-keyed draws reproduce every later vote)."""
    X, y = _data(n=3000, f=16)
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5, "top_k": 6, "tree_learner": "voting",
         "snapshot_freq": 3, "snapshot_keep": 8}
    out = str(tmp_path / "model.txt")
    full = lgb.train(dict(p, output_model=out), lgb.Dataset(X, label=y),
                     num_boost_round=6)
    snap = out + ".snapshot_iter_3"
    assert os.path.exists(snap)
    resumed = lgb.train(dict(p, resume_from=snap, output_model=out),
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert _strip_params(full.model_to_string()) == \
        _strip_params(resumed.model_to_string())


@pytest.mark.slow
def test_voting_close_to_serial():
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5, "max_bin": 63, "top_k": 8}
    serial = lgb.train({**params, "tree_learner": "serial"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    voting = lgb.train({**params, "tree_learner": "voting"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    assert voting.engine._voting, "voting learner should be active"
    mse_s = float(np.mean((serial.predict(X) - y) ** 2))
    mse_v = float(np.mean((voting.predict(X) - y) ** 2))
    var = float(np.var(y))
    # PV-Tree is approximate: demand competitive accuracy, not identity
    assert mse_v < var * 0.2, (mse_v, var)
    assert mse_v < mse_s * 2.0 + 1e-3, (mse_v, mse_s)


@pytest.mark.slow
def test_voting_handles_all_layouts():
    """The PV-Tree learner supports every training layout like the
    reference's (voting_parallel_tree_learner.cpp handles categorical, NaN
    and bundled features): the three test_distributed.py layouts must train
    UNDER voting (no fallback) with competitive accuracy."""
    from tests.test_distributed import _datasets

    for name, params, data_kw, ds_kw in _datasets():
        p = dict(params, num_leaves=15, verbosity=-1, min_data_in_leaf=5,
                 tree_learner="voting", top_k=6)
        ds = lgb.Dataset(data_kw["data"], label=data_kw["label"],
                         weight=data_kw.get("weight"), **ds_kw)
        bst = lgb.train(p, ds, num_boost_round=8)
        assert bst.engine._voting, f"{name}: voting learner should be active"
        serial = lgb.train(dict(p, tree_learner="serial"), lgb.Dataset(
            data_kw["data"], label=data_kw["label"],
            weight=data_kw.get("weight"), **ds_kw), num_boost_round=8)
        pred = np.asarray(bst.predict(data_kw["data"]))
        sref = np.asarray(serial.predict(data_kw["data"]))
        y = np.asarray(data_kw["label"])
        if params["objective"] == "binary":
            acc = float(np.mean((pred > 0.5) == (y > 0.5)))
            acc_s = float(np.mean((sref > 0.5) == (y > 0.5)))
            assert acc > acc_s - 0.05, (name, acc, acc_s)
        else:
            c = np.corrcoef(pred, y)[0, 1]
            c_s = np.corrcoef(sref, y)[0, 1]
            assert c > c_s - 0.05, (name, c, c_s)
