"""Binary wire protocol (docs/SERVING.md "Binary wire protocol").

The wire contract under test:

  * codec roundtrips (request and response frames, trace tail, errors);
  * end-to-end over a live ServingApp: every bucket size bitwise equal
    to ``Booster.predict`` (raw + transformed, binary + multiclass with
    categorical/NaN rows), pipelined bursts included;
  * deadline propagation: an expired budget draws a structured
    deadline frame, never a scored response;
  * malformed-frame fuzz: truncated length prefix, oversize length,
    wrong row width, mid-frame disconnect, junk handshake — each yields
    a structured error frame or a clean close, never a wedged worker
    (the LGB008 discipline applied to the accept loop);
  * HTTP/1.1 keep-alive on the JSON path (connection reuse asserted).
"""
import json
import socket
import struct
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import BinaryClient, ServingApp, WireError
from lightgbm_tpu.serving import wire


def _make_data(seed=7, n=800):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    X[:, 4] = rs.randint(0, 9, n)
    X[rs.rand(n) < 0.15, 0] = np.nan
    y = ((X[:, 1] > 0) ^ (X[:, 4] == 3)).astype(np.float64)
    return X, y


def _train_to_file(path, seed=3, objective="binary", num_class=1):
    X, y = _make_data()
    if num_class > 1:
        rs = np.random.RandomState(seed)
        y = rs.randint(0, num_class, len(y)).astype(np.float64)
    params = {"objective": objective, "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "seed": seed}
    if num_class > 1:
        params["num_class"] = num_class
    bst = lgb.train(params, lgb.Dataset(X, label=y,
                                        categorical_feature=[4]),
                    num_boost_round=6)
    bst.save_model(str(path))
    return X


@pytest.fixture(scope="module")
def servebin(tmp_path_factory):
    """(app, X, ref) — a ServingApp with the binary wire open."""
    td = tmp_path_factory.mktemp("wire")
    mp = td / "model.txt"
    X = _train_to_file(mp)
    app = ServingApp(str(mp), port=0, max_batch=32, max_delay_ms=1.0,
                     queue_size=256, binary_port=0).start()
    yield app, X, lgb.Booster(model_file=str(mp))
    app.shutdown(drain=True)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_request_roundtrip():
    rows = np.arange(12, dtype=np.float64).reshape(3, 4)
    frame = wire.encode_request(42, rows, raw_score=True,
                                deadline_ms=125.5, trace="abc123;s=1")
    (length,) = struct.unpack_from("<I", frame)
    assert length == len(frame) - 4
    req = wire.parse_request(frame[4:])
    assert req["request_id"] == 42
    assert req["raw_score"] and not req["fast"]
    assert req["deadline_ms"] == pytest.approx(125.5)
    assert req["trace"] == "abc123;s=1"
    np.testing.assert_array_equal(req["rows"],
                                  rows.astype(np.float32))


def test_response_roundtrip():
    v = np.asarray([0.125, -3.5, 7.0])
    frame = wire.encode_response_ok(7, v, 3, "ab" * 32)
    resp = wire.parse_response(frame[4:])
    assert resp["status"] == wire.ST_OK
    assert resp["model_version"] == 3
    assert resp["model_sha256"] == "ab" * 32
    np.testing.assert_array_equal(resp["predictions"], v)   # f64 exact

    frame = wire.encode_response_error(9, wire.ST_OVERLOAD, "queue full",
                                       retry_after_s=0.25)
    resp = wire.parse_response(frame[4:])
    assert resp["status"] == wire.ST_OVERLOAD
    assert resp["error"] == "queue full"
    assert resp["retry_after_s"] == pytest.approx(0.25)


def test_parse_request_malformed():
    with pytest.raises(WireError, match="too short"):
        wire.parse_request(b"\x01\x02")
    rows = np.zeros((2, 3))
    frame = wire.encode_request(1, rows)
    with pytest.raises(WireError, match="payload short"):
        wire.parse_request(frame[4:-5])      # truncated rows
    bad_op = bytearray(frame[4:])
    bad_op[4] = 99
    with pytest.raises(WireError, match="unknown wire op"):
        wire.parse_request(bytes(bad_op))


# ---------------------------------------------------------------------------
# end to end
# ---------------------------------------------------------------------------

def test_binary_bitwise_every_bucket(servebin):
    app, X, ref = servebin
    with BinaryClient(app.host, app.binary_port) as c:
        for sz in (1, 2, 7, 8, 9, 31, 32, 33, 200):
            for raw in (True, False):
                resp = c.request(X[:sz], raw_score=raw)
                assert resp["status"] == wire.ST_OK, resp
                want = ref.predict(X[:sz], raw_score=raw)
                got = np.asarray(resp["predictions"])
                assert got.shape == want.shape
                assert np.array_equal(got, want), \
                    f"size {sz} raw={raw}: |diff| {np.abs(got-want).max()}"
                assert resp["model_sha256"] == app.registry.current().sha256


def test_binary_multiclass_bitwise(tmp_path):
    mp = tmp_path / "mc.txt"
    X = _train_to_file(mp, objective="multiclass", num_class=3)
    ref = lgb.Booster(model_file=str(mp))
    app = ServingApp(str(mp), port=0, max_batch=16, max_delay_ms=1.0,
                     binary_port=0).start()
    try:
        with BinaryClient(app.host, app.binary_port) as c:
            for sz in (1, 5, 17):
                for raw in (True, False):
                    resp = c.request(X[:sz], raw_score=raw)
                    assert resp["status"] == wire.ST_OK
                    assert np.array_equal(
                        np.asarray(resp["predictions"]),
                        ref.predict(X[:sz], raw_score=raw))
    finally:
        app.shutdown(drain=True)


def test_binary_pipelined_burst(servebin):
    """Many frames in flight coalesce into batcher dispatches; every
    response still matches its request bitwise."""
    app, X, ref = servebin
    want = ref.predict(X[:200], raw_score=True)
    with BinaryClient(app.host, app.binary_port) as c:
        spans = [(int(s), int(s + m)) for s, m in
                 zip(np.arange(0, 180, 3), [1, 2, 5] * 20)]
        resps = c.pipeline([X[s:e] for s, e in spans], raw_score=True)
        for (s, e), resp in zip(spans, resps):
            assert resp["status"] == wire.ST_OK
            assert np.array_equal(np.asarray(resp["predictions"]),
                                  want[s:e])


def test_binary_fast_flag_and_trace_echo(servebin):
    app, X, ref = servebin
    with BinaryClient(app.host, app.binary_port) as c:
        resp = c.request(X[:1], raw_score=True, fast=True,
                         trace="cafe01;s=0")
        assert resp["status"] == wire.ST_OK
        assert np.array_equal(np.asarray(resp["predictions"]),
                              ref.predict(X[:1], raw_score=True))


def test_binary_deadline_expired(servebin):
    app, X, _ = servebin
    with BinaryClient(app.host, app.binary_port) as c:
        # 1e-3 ms: expired before admission — structured frame, no score
        resp = c.request(X[:4], deadline_ms=1e-3)
        assert resp["status"] == wire.ST_DEADLINE
        assert "deadline" in resp["error"]
        # the connection keeps serving afterwards
        resp = c.request(X[:4])
        assert resp["status"] == wire.ST_OK


def test_binary_wrong_row_width(servebin):
    app, X, _ = servebin
    with BinaryClient(app.host, app.binary_port) as c:
        resp = c.request(np.zeros((2, 3)))           # model has 6 features
        assert resp["status"] == wire.ST_BAD_REQUEST
        assert "features" in resp["error"]
        resp = c.request(X[:2])                      # conn still healthy
        assert resp["status"] == wire.ST_OK


# ---------------------------------------------------------------------------
# malformed-frame fuzz: the accept loop never wedges
# ---------------------------------------------------------------------------

def _raw_conn(app):
    s = socket.create_connection((app.host, app.binary_port), timeout=10)
    s.sendall(wire.HANDSHAKE)
    hello = s.recv(8)
    assert hello[:4] == wire.MAGIC
    return s


def _assert_still_serving(app, X):
    with BinaryClient(app.host, app.binary_port) as c:
        assert c.request(X[:2])["status"] == wire.ST_OK
    assert app.batcher.worker_alive


def test_fuzz_truncated_length_prefix(servebin):
    app, X, _ = servebin
    s = _raw_conn(app)
    s.sendall(b"\x07")            # 1 of 4 length bytes, then vanish
    s.close()
    _assert_still_serving(app, X)


def test_fuzz_oversize_length(servebin):
    app, X, _ = servebin
    s = _raw_conn(app)
    s.sendall(struct.pack("<I", 2 ** 31 - 1))
    f = s.makefile("rb")
    head = f.read(4)              # structured refusal frame, then close
    assert head, "server closed without an error frame"
    (length,) = struct.unpack("<I", head)
    resp = wire.parse_response(f.read(length))
    assert resp["status"] == wire.ST_BAD_REQUEST
    assert "length" in resp["error"]
    assert f.read(1) == b""       # connection closed after the refusal
    s.close()
    _assert_still_serving(app, X)


def test_fuzz_mid_frame_disconnect(servebin):
    app, X, _ = servebin
    s = _raw_conn(app)
    frame = wire.encode_request(5, X[:8])
    s.sendall(frame[:len(frame) // 2])    # half a frame, then vanish
    s.close()
    _assert_still_serving(app, X)


def test_fuzz_garbage_header_payload(servebin):
    app, X, _ = servebin
    s = _raw_conn(app)
    s.sendall(struct.pack("<I", 16) + b"\xff" * 16)   # bad op byte
    f = s.makefile("rb")
    head = f.read(4)
    (length,) = struct.unpack("<I", head)
    resp = wire.parse_response(f.read(length))
    assert resp["status"] == wire.ST_BAD_REQUEST
    s.close()
    _assert_still_serving(app, X)


def test_fuzz_junk_handshake(servebin):
    app, X, _ = servebin
    s = socket.create_connection((app.host, app.binary_port), timeout=10)
    s.sendall(b"GET / HTTP/1.1\r\n\r\n")   # an HTTP client on the wire port
    assert s.recv(64) == b""               # silently closed, nothing leaked
    s.close()
    _assert_still_serving(app, X)


def test_binary_stats_surface(servebin):
    """Self-sufficient (no reliance on sibling tests having run): drive
    one good request and one bad frame, then assert the counters."""
    app, X, _ = servebin
    before = app.binary.stats()
    with BinaryClient(app.host, app.binary_port) as c:
        assert c.request(X[:2])["status"] == wire.ST_OK
    s = _raw_conn(app)
    s.sendall(struct.pack("<I", 16) + b"\xff" * 16)   # bad op byte
    s.close()
    deadline = time.time() + 5
    while time.time() < deadline:
        st = app.binary.stats()
        if (st["bad_frames"] > before["bad_frames"]
                and st["requests"] > before["requests"]):
            break
        time.sleep(0.02)
    assert st["requests"] > before["requests"]
    assert st["connections"] > before["connections"]
    assert st["bad_frames"] > before["bad_frames"]


# ---------------------------------------------------------------------------
# HTTP keep-alive satellite: the JSON path reuses connections
# ---------------------------------------------------------------------------

def test_http_keepalive_connection_reuse(servebin):
    import http.client

    app, X, ref = servebin
    conn = http.client.HTTPConnection(app.host, app.port, timeout=15)
    try:
        socks = []
        for _ in range(3):
            conn.request("POST", "/predict",
                         json.dumps({"rows": X[:3].tolist(),
                                     "raw_score": True}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            obj = json.loads(r.read())
            assert r.status == 200
            assert np.array_equal(np.asarray(obj["predictions"]),
                                  ref.predict(X[:3], raw_score=True))
            socks.append(conn.sock)
        # HTTP/1.1 keep-alive: one TCP connection served all three
        # requests (a Connection: close server would null conn.sock
        # after each response and reconnect)
        assert socks[0] is not None
        assert all(s is socks[0] for s in socks), \
            "connection was re-established between requests"
    finally:
        conn.close()


def test_binary_draining_refusal(tmp_path):
    mp = tmp_path / "m.txt"
    X = _train_to_file(mp, seed=5)
    app = ServingApp(str(mp), port=0, max_batch=16, binary_port=0).start()
    c = BinaryClient(app.host, app.binary_port)
    try:
        assert c.request(X[:2])["status"] == wire.ST_OK
        app._draining = True
        resp = c.request(X[:2])
        assert resp["status"] == wire.ST_DRAINING
    finally:
        app._draining = False
        c.close()
        app.shutdown(drain=True)
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# v2 negotiation + model-id routing fuzz (multi-tenant wire)
# ---------------------------------------------------------------------------

def test_v2_model_id_codec_roundtrip():
    rows = np.arange(8, dtype=np.float64).reshape(2, 4)
    frame = wire.encode_request(7, rows, model_id="tenant-a",
                                op=wire.OP_EXPLAIN)
    req = wire.parse_request(frame[4:])
    assert req["model_id"] == "tenant-a"
    assert req["op"] == wire.OP_EXPLAIN
    ok = wire.encode_response_ok(7, np.zeros(2), 3, "a" * 64,
                                 model_id="tenant-a")
    (length,) = struct.unpack_from("<I", ok)
    resp = wire.parse_response(ok[4:4 + length])
    assert resp["model_id"] == "tenant-a"
    err = wire.encode_response_error(7, wire.ST_OVERLOAD, "busy",
                                     retry_after_s=0.5, model_id="t")
    (length,) = struct.unpack_from("<I", err)
    assert wire.parse_response(err[4:4 + length])["model_id"] == "t"


def test_v1_codec_refuses_v2_features():
    rows = np.ones((1, 3))
    with pytest.raises(WireError, match="wire v2"):
        wire.encode_request(1, rows, model_id="a", version=1)
    with pytest.raises(WireError, match="wire v2"):
        wire.encode_request(1, rows, op=wire.OP_EXPLAIN, version=1)
    # v1 frames carry no model field and still roundtrip
    frame = wire.encode_request(1, rows, version=1)
    req = wire.parse_request(frame[4:], version=1)
    assert req["model_id"] == "" and req["op"] == wire.OP_PREDICT


def test_version0_hello_structured_refusal(servebin):
    """A hello below VERSION_MIN draws a structured rid-0 refusal frame
    (not a silent close): the client can surface WHY it was refused."""
    app, X, _ = servebin
    s = socket.create_connection((app.host, app.binary_port), timeout=10)
    s.sendall(wire.MAGIC + bytes([0, 0, 0, 0]))
    f = s.makefile("rb")
    head = f.read(4)
    assert len(head) == 4, "server closed without a refusal frame"
    (length,) = struct.unpack("<I", head)
    resp = wire.parse_response(f.read(length), version=1)
    assert resp["request_id"] == 0
    assert resp["status"] == wire.ST_BAD_REQUEST
    assert "version" in resp["error"]
    s.close()
    _assert_still_serving(app, X)


def test_v1_client_on_v2_server(servebin):
    """Explicit v1 clients negotiate down and keep working unchanged."""
    app, X, ref = servebin
    with BinaryClient(app.host, app.binary_port, version=1) as c:
        assert c.version == 1
        resp = c.request(X[:5], raw_score=True)
        assert resp["status"] == wire.ST_OK
        assert np.array_equal(resp["predictions"],
                              ref.predict(X[:5], raw_score=True))


def test_v2_client_downgrades_to_v1_only_server():
    """A pre-v2 replica silently closes an unknown hello; the client
    must retry the handshake at v1 on a fresh connection, not fail."""
    import threading

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            try:
                hello = conn.recv(8)
                if len(hello) < 8 or hello[4] != 1:
                    conn.close()      # v1-only server: unknown hello
                    continue
                conn.sendall(wire.handshake(1))
                f = conn.makefile("rb")
                head = f.read(4)
                (length,) = struct.unpack("<I", head)
                req = wire.parse_request(f.read(length), version=1)
                conn.sendall(wire.encode_response_ok(
                    req["request_id"], np.zeros(req["rows"].shape[0]),
                    1, "f" * 64, version=1))
            except (OSError, WireError):
                pass
            finally:
                conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        c = BinaryClient("127.0.0.1", port)
        assert c.version == 1        # downgraded after the silent close
        resp = c.request(np.ones((3, 2)))
        assert resp["status"] == wire.ST_OK
        assert resp["model_version"] == 1
        c.close()
    finally:
        stop.set()
        srv.close()
        t.join(2)


def test_fuzz_truncated_model_field(servebin):
    """A v2 frame whose model-id length byte overruns the payload is a
    structured bad-request, never a wedged or crashed worker."""
    app, X, _ = servebin
    s = _raw_conn(app)
    rows = np.ascontiguousarray(X[:2], dtype="<f4")
    head = struct.pack("<IBBHIf", 9, wire.OP_PREDICT, 0,
                       rows.shape[1], rows.shape[0], 0.0)
    payload = head + bytes([200]) + b"ab"   # claims 200 bytes, has 2
    s.sendall(struct.pack("<I", len(payload)) + payload)
    f = s.makefile("rb")
    (length,) = struct.unpack("<I", f.read(4))
    resp = wire.parse_response(f.read(length))
    assert resp["status"] == wire.ST_BAD_REQUEST
    s.close()
    _assert_still_serving(app, X)


def test_wire_unknown_model_id_refusal(servebin):
    """model_id routing on a single-model server: a structured refusal
    naming the unknown tenant, and the connection stays usable."""
    app, X, _ = servebin
    with BinaryClient(app.host, app.binary_port) as c:
        resp = c.request(X[:2], model_id="no-such-tenant")
        assert resp["status"] == wire.ST_BAD_REQUEST
        assert "model_id" in resp["error"]
        assert c.request(X[:2])["status"] == wire.ST_OK
